"""Speculative decode + shared prefix cache (docs/SERVING.md
"Speculative decode & prefix sharing").

Pins the PR's non-negotiable contracts:

- BIT-EXACT speculation: a spec-decoded stream emits the identical
  token sequence plain greedy decode emits — across slot ladders,
  spec_k widths, mid-stream joins/leaves, and both decode models
  (RNN + GQA transformer);
- acceptance can only shorten steps: tokens/step > 1.3 on the
  repeated-suffix workload the drafter is built for;
- hash-collision safety: a constant prefix hash may cause lookups to
  scan, never to alias two different prefixes (byte verification);
- COW concurrent divergence: two requests writing into the same
  shared partial page diverge without corrupting each other;
- refcount-exact frees: shed/EOS returns exactly the private tail; a
  shared page frees with its LAST holder and its registry entries die
  with it;
- ~1/N physical pages for N requests over one shared prefix, and
  allocator bytes == census bytes throughout (one accounting path);
- the guarded zero-sync run: 12+ spec+shared iterations under
  MXNET_TRANSFER_GUARD=raise with retire as the ONE blessed sync;
- verify programs AOT-compile at warmup (no live traces under load);
- GQA: the broadcast attention matches an explicit repeated-KV
  reference and the engine sizes the cache by num_kv_heads.
"""
import numpy as onp
import pytest

import jax.numpy as jnp

from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (DecodeEngine, NgramDrafter, PagedKVCache,
                               TinyDecoder, pages_needed)
from mxnet_tpu.serving import kvcache as kvcache_mod
from mxnet_tpu.serving.decode import _spec_k_valid

VOCAB = 48


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float):
        self.t += dt


@pytest.fixture(scope="module")
def model():
    return TinyDecoder(vocab=VOCAB, d_model=32, num_heads=2, seed=0)


@pytest.fixture(scope="module")
def gqa_model():
    from mxnet_tpu.gluon import GQADecoder
    return GQADecoder(vocab=VOCAB, d_model=16, num_heads=4,
                      num_kv_heads=2, num_layers=2, seed=1)


def make_engine(model, **kw):
    kw.setdefault("ladder", (1, 2))
    kw.setdefault("page_size", 4)
    kw.setdefault("max_context", 64)
    kw.setdefault("num_pages", 96)
    kw.setdefault("start", False)
    kw.setdefault("spec_k", 0)
    kw.setdefault("prefix_share", False)
    return DecodeEngine(model, **kw)


def drive(eng, max_iters: int = 400) -> int:
    it = 0
    while it < max_iters:
        did = eng.step_once()
        eng.sync()
        if not did and eng._idle():
            return it
        it += 1
    raise AssertionError(f"engine did not go idle in {max_iters} iters")


def prompt(seed: int, n: int):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, size=n).astype(onp.int32)


def decode_all(model, prompts, mns, **kw):
    eng = make_engine(model, **kw)
    try:
        streams = [eng.submit(p, max_new=m)
                   for p, m in zip(prompts, mns)]
        drive(eng)
        return [s.result(0) for s in streams]
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# drafters
# ---------------------------------------------------------------------------

def test_ngram_drafter_prompt_lookup():
    d = NgramDrafter(n=2)
    # last bigram (3, 4) occurred earlier, followed by 5, 6
    assert d.propose([1, 2, 3, 4, 5, 6, 9, 3, 4], 2) == [5, 6]
    # most RECENT earlier occurrence wins
    assert d.propose([3, 4, 7, 3, 4, 8, 3, 4], 1) == [8]
    # falls back to shorter n-grams before giving up
    assert d.propose([5, 1, 9, 9, 2, 1], 1) == [9]
    assert d.propose([1, 2, 3], 0) == []
    # no earlier occurrence of any suffix -> nothing proposed
    assert d.propose([1, 2, 3, 4], 3) == []


def test_ngram_drafter_k_caps_proposal():
    d = NgramDrafter(n=1)
    hist = [7, 1, 2, 3, 4, 7]
    assert d.propose(hist, 2) == [1, 2]
    assert len(d.propose(hist, 10)) <= 10


# ---------------------------------------------------------------------------
# bit-exact speculation
# ---------------------------------------------------------------------------

_GREEDY = {}


def greedy_baseline(model, prompts, mns, ladder):
    if ladder not in _GREEDY:
        _GREEDY[ladder] = decode_all(model, prompts, mns,
                                     ladder=ladder)
    return _GREEDY[ladder]


@pytest.mark.parametrize("ladder,spec_k",
                         [((1,), 1), ((1, 2), 3), ((1, 2, 4), 6)])
def test_spec_bitexact_across_ladders(model, ladder, spec_k):
    """The pinned contract: speculative streams emit token sequences
    BIT-identical to plain greedy decode, for every ladder bucket and
    draft width — requests outnumber slots so slots join/leave
    mid-run."""
    prompts = [prompt(10 + i, 2 + (i % 5)) for i in range(5)]
    mns = [6, 11, 4, 9, 7]
    greedy = greedy_baseline(model, prompts, mns, ladder)
    spec = decode_all(model, prompts, mns, ladder=ladder,
                      spec_k=spec_k)
    assert spec == greedy


def test_spec_bitexact_midstream_joins_and_leaves(model):
    """Requests submitted WHILE earlier ones are mid-decode (and
    finishing at different times) still stream bit-exact sequences.
    The baseline is the cached batch-submitted greedy run: neither
    speculation nor submit staggering may change a single token."""
    prompts = [prompt(10 + i, 2 + (i % 5)) for i in range(5)]
    mns = [6, 11, 4, 9, 7]
    eng = make_engine(model, ladder=(1, 2, 4), spec_k=4)
    try:
        streams = [eng.submit(prompts[0], max_new=mns[0]),
                   eng.submit(prompts[1], max_new=mns[1])]
        for _ in range(6):                # both mid-flight
            eng.step_once()
            eng.sync()
        streams.append(eng.submit(prompts[2], max_new=mns[2]))
        for _ in range(4):
            eng.step_once()
            eng.sync()
        streams += [eng.submit(p, max_new=m)
                    for p, m in zip(prompts[3:], mns[3:])]
        drive(eng)
        got = [s.result(0) for s in streams]
    finally:
        eng.close()
    assert got == greedy_baseline(model, prompts, mns, (1, 2, 4))


def test_spec_emits_multitoken_steps_on_repetitive_output(model):
    """tokens/step > 1.3 on the repeated-suffix workload (the engine's
    greedy output cycles, which prompt-lookup drafting predicts
    exactly after a warm-up prefix)."""
    prompts = [prompt(60 + i, 4) for i in range(3)]
    res = serving.run_decode(model, prompts, 24, ladder=(1, 2, 4),
                             page_size=4, spec_k=4,
                             prefix_share=False, warmup=False)
    assert res["spec_drafted"] > 0 and res["spec_accepted"] > 0
    tps = res["tokens_per_step"]["mean"]
    assert tps > 1.3, f"tokens/step {tps} <= 1.3"
    assert res["acceptance_rate"] is not None
    # steps can only SHRINK vs greedy, never tokens
    greedy = serving.run_decode(model, prompts, 24, ladder=(1, 2, 4),
                                page_size=4, spec_k=0,
                                prefix_share=False, warmup=False)
    assert res["tokens"] == greedy["tokens"]


def test_spec_stream_record_and_loadgen_summary(model):
    from mxnet_tpu.serving import loadgen
    eng = make_engine(model, spec_k=3)
    try:
        s = eng.submit(prompt(70, 4), max_new=10)
        drive(eng)
        rec = s.record()
    finally:
        eng.close()
    # the first token lands at prefill retire; every later one is a
    # verify step, so step_tokens accounts for exactly tokens - 1
    assert rec["tokens"] == 10
    assert sum(rec["step_tokens"]) == rec["tokens"] - 1
    assert rec["spec_accepted"] <= rec["spec_drafted"]
    summ = loadgen.streaming_summary([rec], 1.0)
    assert "tokens_per_step" in summ
    assert summ["tokens_per_step"]["mean"] == pytest.approx(
        sum(rec["step_tokens"]) / len(rec["step_tokens"]), rel=1e-6)
    if rec["spec_drafted"]:
        assert summ["acceptance_rate"] == pytest.approx(
            rec["spec_accepted"] / rec["spec_drafted"], rel=1e-6)
    # plain-greedy records leave the spec view out entirely
    assert "tokens_per_step" not in loadgen.streaming_summary(
        [{"tokens": 3, "ttft_s": 0.1, "tpot_s": [0.01]}], 1.0)


def test_verify_program_aot_compiled_at_warmup(model):
    eng = make_engine(model, ladder=(1, 2), spec_k=2)
    try:
        exes = eng.warmup()
        assert set(exes) == {("decode", 1), ("decode", 2),
                             ("prefill", 1), ("prefill", 2),
                             ("verify", 1), ("verify", 2)}
        assert eng.n_traces == 0
        streams = [eng.submit(prompt(80 + i, 3), max_new=6)
                   for i in range(2)]
        drive(eng)
        for s in streams:
            assert len(s.result(0)) == 6
        assert eng.n_traces == 0, "verify must serve from AOT"
    finally:
        eng.close()


def test_spec_accounting_and_accept_hist(model):
    eng = make_engine(model, spec_k=4)
    try:
        s = eng.submit(prompt(90, 4), max_new=12)
        drive(eng)
        assert len(s.result(0)) == 12
        st = eng.stats
        assert st["spec_steps"] > 0
        assert st["spec_accepted"] <= st["spec_drafted"]
        hist = st["accept_hist"]
        assert sum(hist.values()) == st["spec_steps"]
        # each step accepts its block of a = accepted-drafts + 1 tokens
        assert sum(n * c for n, c in hist.items()) == \
            st["spec_accepted"] + st["spec_steps"]
        assert all(1 <= n <= 5 for n in hist)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# tunables
# ---------------------------------------------------------------------------

def test_spec_tunables_registered():
    from mxnet_tpu.tuning import space
    names = {t["name"]: t for t in space.table()}
    assert tuple(names["decode.spec_k"]["grid"]) == (0, 2, 4, 8)
    assert names["decode.spec_k"]["scope"] == "serving"
    assert space.get("decode.spec_k").affects_program is True
    assert tuple(names["decode.prefix_share"]["grid"]) == (0, 1)
    assert space.get("decode.prefix_share").affects_program is False


def test_spec_env_overrides(monkeypatch):
    monkeypatch.setenv("MXNET_DECODE_SPEC_K", "6")
    monkeypatch.setenv("MXNET_DECODE_PREFIX_SHARE", "0")
    assert serving.spec_k() == 6
    assert serving.prefix_share() is False
    monkeypatch.setenv("MXNET_DECODE_SPEC_K", "garbage")
    assert serving.spec_k() == serving.decode.SPEC_K


def test_spec_k_validity_respects_memory_budget(monkeypatch):
    assert _spec_k_valid(0, None)
    assert _spec_k_valid(8, None)
    assert not _spec_k_valid(-1, None)
    assert not _spec_k_valid(65, None)
    assert not _spec_k_valid("x", None)
    monkeypatch.setenv("MXNET_MEMORY_BUDGET", str(16 * 1024))
    assert not _spec_k_valid(8, None), \
        "speculative slack must be priced against the KV budget"
    assert _spec_k_valid(0, None), "off is always affordable"


def test_engine_reads_spec_env(monkeypatch, model):
    monkeypatch.setenv("MXNET_DECODE_SPEC_K", "3")
    monkeypatch.setenv("MXNET_DECODE_PREFIX_SHARE", "0")
    eng = make_engine(model, spec_k=None, prefix_share=None)
    try:
        assert eng._spec_k == 3 and eng._prefix_share is False
        assert isinstance(eng._drafter, NgramDrafter)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# prefix cache: allocator-level contracts
# ---------------------------------------------------------------------------

def test_share_refcounts_and_last_holder_frees():
    kv = PagedKVCache(1, 2, 16, num_pages=8, page_size=4)
    a, b = object(), object()
    pa = kv.alloc(a, 3)
    kv.register_prefix([1, 2, 3, 4, 5], 5, pa[:2])
    kv.share(b, pa[:2])
    kv.alloc(b, 1)
    assert kv.used_pages() == 4          # physical: shared counted once
    assert kv.logical_pages() == 6       # per-holder view
    assert kv.shared_pages() == 2
    assert kv.release(a) == 1            # only a's private page frees
    assert kv.used_pages() == 3
    assert kv.prefix_entries() == 1      # entry survives with b
    assert kv.release(b) == 3            # last holder frees the rest
    assert kv.used_pages() == 0 and kv.free_pages() == 7
    assert kv.prefix_entries() == 0, \
        "registry entries must die with their last page holder"


def test_share_rejects_unallocated_page():
    kv = PagedKVCache(1, 2, 16, num_pages=8, page_size=4)
    with pytest.raises(MXNetError, match="not allocated"):
        kv.share(object(), [3])


def test_cow_swaps_page_and_drops_refcount():
    kv = PagedKVCache(1, 2, 16, num_pages=8, page_size=4)
    a, b = object(), object()
    (p,) = kv.alloc(a, 1)
    kv.k_pages._data = kv.k_pages._data.at[:, p].set(7.0)
    kv.share(b, [p])
    assert kv.page_shared(p)
    new = kv.cow(b, p)
    assert new != p and not kv.page_shared(p)
    assert kv.pages_of(b) == [new] and kv.pages_of(a) == [p]
    assert kv.cow_copies == 1
    # the copy carries the page CONTENT
    assert float(jnp.max(jnp.abs(
        kv.k_pages._data[:, new] - kv.k_pages._data[:, p]))) == 0.0


def test_lookup_prefix_byte_verifies_under_hash_collision(monkeypatch):
    """A constant hash maps every prefix to one bucket; byte
    verification alone must keep lookups exact."""
    monkeypatch.setattr(kvcache_mod, "prefix_hash", lambda toks: 7)
    kv = PagedKVCache(1, 2, 16, num_pages=8, page_size=4)
    a, b = object(), object()
    pa = kv.alloc(a, 2)
    pb = kv.alloc(b, 2)
    kv.register_prefix([1, 2, 3, 4, 5], 5, pa)
    kv.register_prefix([9, 8, 7, 6, 5], 5, pb)
    hit = kv.lookup_prefix(onp.asarray([1, 2, 3, 4, 5, 6]))
    assert hit is not None and hit.pages == tuple(pa)
    hit = kv.lookup_prefix(onp.asarray([9, 8, 7, 6, 5, 1]))
    assert hit is not None and hit.pages == tuple(pb)
    assert kv.lookup_prefix(onp.asarray([1, 2, 3, 9, 5, 6])) is None


def test_engine_bitexact_under_hash_collision(model, monkeypatch):
    """End-to-end collision drill: every prefix hashes identically and
    shared-prefix decode output must still match the no-share run."""
    base = prompt(100, 9)

    def run(share):
        eng = make_engine(model, ladder=(1, 2), spec_k=0,
                          prefix_share=share)
        try:
            s1 = eng.submit(base, max_new=10)
            for _ in range(4):
                eng.step_once()
                eng.sync()
            s2 = eng.submit(onp.concatenate([base, [3, 4]]),
                            max_new=8)
            drive(eng)
            hits = eng.stats["prefix_hits"]
            return [s1.result(0), s2.result(0)], hits
        finally:
            eng.close()

    expect, _ = run(False)
    monkeypatch.setattr(kvcache_mod, "prefix_hash", lambda toks: 7)
    got, hits = run(True)
    assert got == expect
    assert hits >= 1, "byte-equal prefix must still hit under collision"


# ---------------------------------------------------------------------------
# prefix cache: engine-level contracts
# ---------------------------------------------------------------------------

def with_tail(base, tail):
    return onp.concatenate(
        [base, onp.asarray(tail, onp.int32)]).astype(onp.int32)


def shared_run(model, base, tails, mns, *, share, spec_k=0,
               warm_iters=4, ladder=(1, 2, 4), stats_out=None):
    """Donor decodes over ``base + tails[0]``; joiners (submitted only
    after the donor's prefill retires and registers its prompt in the
    content-hash registry) extend the same prefix."""
    eng = make_engine(model, ladder=ladder, spec_k=spec_k,
                      prefix_share=share)
    try:
        streams = [eng.submit(with_tail(base, tails[0]),
                              max_new=mns[0])]
        for _ in range(warm_iters):      # register the donor's prefix
            eng.step_once()
            eng.sync()
        streams += [eng.submit(with_tail(base, t), max_new=m)
                    for t, m in zip(tails[1:], mns[1:])]
        drive(eng)
        if stats_out is not None:
            stats_out.update(eng.stats)
            stats_out["kv"] = eng.kv.stats()
        return [s.result(0) for s in streams]
    finally:
        eng.close()


def test_prefix_share_bitexact_with_rnn_state_resume(model):
    """A joiner seated mid-prefix resumes from the donor's recurrent
    state snapshot — output must be bit-identical to recomputing the
    whole prompt."""
    base = prompt(110, 11)               # partial page: 11 % 4 != 0
    tails, mns = ([], [2, 9], [7, 3]), (12, 8, 8)
    st = {}
    plain = shared_run(model, base, tails, mns, share=False)
    shared = shared_run(model, base, tails, mns, share=True,
                        stats_out=st)
    assert shared == plain
    assert st["prefix_hits"] == 2
    assert st["prefix_tokens"] > 0
    assert st["kv_shared_peak"] >= 1


def test_spec_and_share_compose_bitexact(model):
    base = prompt(120, 10)
    tails, mns = ([], [6, 2], [1, 8]), (10, 10, 6)
    plain = shared_run(model, base, tails, mns, share=False,
                       ladder=(1, 4))
    both = shared_run(model, base, tails, mns, share=True, spec_k=4,
                      ladder=(1, 4))
    assert both == plain


def test_cow_concurrent_divergence_same_page(model):
    """The donor keeps decoding INTO the page a joiner just mapped
    (and the joiner prefills its divergent tail into it): both must
    COW privately and neither stream may corrupt the other."""
    base = prompt(130, 10)               # page 2 partial (10 % 4 = 2)
    st = {}
    # joiner extends the donor's FULL prompt -> shares the partial page
    plain = shared_run(model, base, ([], [9, 9, 1]), (14, 10),
                       share=False, warm_iters=6, ladder=(1, 2))
    shared = shared_run(model, base, ([], [9, 9, 1]), (14, 10),
                        share=True, warm_iters=6, ladder=(1, 2),
                        stats_out=st)
    assert shared == plain
    assert st["prefix_hits"] == 1
    assert st["kv"]["cow_copies"] >= 1, \
        "divergence inside a shared page must copy-on-write"


def test_refcount_exact_frees_on_shed_and_eos(model):
    """A mid-run shed (deadline) releases exactly the shed request's
    private tail: the donor keeps its pages, finishes bit-exact, and
    the pool drains to zero afterwards."""
    base = prompt(140, 9)
    plain = shared_run(model, base, ([],), (16,), share=False,
                       ladder=(1, 2))
    clk = FakeClock()
    eng = make_engine(model, ladder=(1, 2), prefix_share=True,
                      clock=clk)
    try:
        s1 = eng.submit(base, max_new=16)
        for _ in range(4):
            eng.step_once()
            eng.sync()
        # the joiner's deadline expires mid-decode: it sheds while
        # still holding shared prefix pages
        s2 = eng.submit(with_tail(base, [2, 2]), max_new=16,
                        deadline_ms=100.0)
        for _ in range(3):
            eng.step_once()
            eng.sync()
        clk.advance(10.0)                # way past the joiner deadline
        drive(eng)
        assert eng.stats["prefix_hits"] == 1
        assert eng.stats["deadline_missed"] == 1
        with pytest.raises(Exception):
            s2.result(0)
        assert s1.result(0) == plain[0], \
            "shedding a prefix-sharing neighbour corrupted the donor"
        assert eng.kv.used_pages() == 0
        assert eng.kv.shared_pages() == 0
        assert eng.kv.free_pages() == eng.kv.num_pages - 1
        assert not eng.kv._refcnt, "refcounts must drain to empty"
    finally:
        eng.close()


def test_shared_census_approaches_one_over_n(model):
    """N requests over one long shared prefix hold ~1/N the physical
    pages of N private copies: census-pinned page counts."""
    ps = 4
    base = prompt(150, 24)               # 6 full pages of shared prefix
    n = 4
    eng = make_engine(model, ladder=(1, 2, 4, 8), page_size=ps,
                      prefix_share=True, num_pages=160,
                      max_context=64)
    try:
        streams = [eng.submit(base, max_new=12)]
        for _ in range(8):
            eng.step_once()
            eng.sync()
        streams += [eng.submit(with_tail(base, [i, 2]),
                               max_new=12) for i in range(1, n)]
        # run until every request is seated and mid-decode
        for _ in range(6):
            eng.step_once()
            eng.sync()
        kv = eng.kv.stats()
        assert eng.stats["prefix_hits"] == n - 1
        # the 6 full base pages exist ONCE physically but n times
        # logically: logical - physical == (n-1) * 6
        assert kv["logical_pages"] - kv["used_pages"] == (n - 1) * 6
        assert kv["shared_pages"] == 6
        drive(eng)
        outs = [s.result(0) for s in streams]
        assert all(len(o) == 12 for o in outs)
        assert eng.kv.used_pages() == 0
    finally:
        eng.close()


def test_allocator_bytes_equal_census_bytes_with_sharing(model):
    """COW rebinds the page arrays' _data mid-run; the census handles
    must survive and the one-accounting-path equality must hold while
    shares and copies are live."""
    base = prompt(160, 10)
    eng = make_engine(model, ladder=(1, 2), prefix_share=True)
    try:
        census = telemetry.memory.census()
        s1 = eng.submit(base, max_new=12)
        for _ in range(5):
            eng.step_once()
            eng.sync()
        s2 = eng.submit(onp.concatenate([base, [1, 4]]), max_new=8)
        for _ in range(6):
            eng.step_once()
            eng.sync()
        pool = census.live_bytes_by_pool().get("kvcache", 0)
        assert pool >= eng.kv.total_bytes() > 0
        drive(eng)
        s1.result(0), s2.result(0)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# the guarded zero-sync spec+shared run
# ---------------------------------------------------------------------------

def test_spec_share_run_zero_unblessed_syncs(model, monkeypatch):
    """12+ scheduler iterations of draft->verify + prefix sharing under
    MXNET_TRANSFER_GUARD=raise: COW copies and acceptance rollback are
    device-side; the retire stays the ONE blessed sync."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    base = prompt(170, 9)
    eng = make_engine(model, ladder=(1, 4), spec_k=4,
                      prefix_share=True)
    try:
        eng.warmup()
        before = telemetry.value(telemetry.names.HOST_SYNCS,
                                 "wait_to_read") or 0
        streams = [eng.submit(base, max_new=14)]
        for _ in range(4):
            eng.step_once()
            eng.sync()
        streams += [eng.submit(with_tail(base, [i, 7]),
                               max_new=10) for i in range(2)]
        iters = drive(eng)
        after = telemetry.value(telemetry.names.HOST_SYNCS,
                                "wait_to_read") or 0
        assert iters + 4 >= 12
        assert [len(s.result(0)) for s in streams] == [14, 10, 10]
        assert after - before == 0, \
            "spec+share hot loop performed an unblessed host sync"
        assert eng.stats["spec_steps"] > 0
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# GQA transformer decode stack
# ---------------------------------------------------------------------------

def test_gqa_rejects_bad_geometry():
    from mxnet_tpu.gluon import GQADecoder
    with pytest.raises(MXNetError, match="multiple"):
        GQADecoder(d_model=32, num_heads=4, num_kv_heads=3)
    with pytest.raises(MXNetError, match="divisible"):
        GQADecoder(d_model=30, num_heads=4, num_kv_heads=2)


def test_gqa_engine_sizes_cache_by_kv_heads(gqa_model):
    eng = make_engine(gqa_model)
    try:
        assert eng.kv.num_heads == gqa_model.num_kv_heads == 2
        assert eng.kv.num_layers == gqa_model.num_layers == 2
        # dummy carries: (slots, 1) pass-throughs
        assert eng._h.shape == (eng.slots, 1)
    finally:
        eng.close()


def test_gqa_attention_matches_repeated_kv_reference():
    """paged_decode_attention with fewer K/V heads must equal the MHA
    result over explicitly repeated K/V heads."""
    from mxnet_tpu.ops.attention import paged_decode_attention
    rng = onp.random.RandomState(0)
    S, Hq, Hkv, D, P, ps = 3, 4, 2, 8, 6, 4
    q = jnp.asarray(rng.normal(size=(S, Hq, D)).astype("float32"))
    kp = jnp.asarray(rng.normal(size=(P, ps, Hkv, D)).astype("float32"))
    vp = jnp.asarray(rng.normal(size=(P, ps, Hkv, D)).astype("float32"))
    table = jnp.asarray(
        onp.array([[1, 2, 0], [3, 4, 0], [5, 1, 0]], onp.int32))
    lengths = jnp.asarray([7, 5, 2], jnp.int32)
    out = paged_decode_attention(q, kp, vp, table, lengths)
    rep = jnp.repeat(kp, Hq // Hkv, axis=2), \
        jnp.repeat(vp, Hq // Hkv, axis=2)
    ref = paged_decode_attention(q, rep[0], rep[1], table, lengths)
    assert out.shape == (S, Hq, D)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-6, atol=1e-6)


def test_gqa_attention_rejects_nondivisible_heads():
    from mxnet_tpu.ops.attention import paged_decode_attention
    q = jnp.zeros((2, 4, 8), "float32")
    kp = jnp.zeros((4, 4, 3, 8), "float32")
    with pytest.raises(MXNetError, match="multiple|divis"):
        paged_decode_attention(q, kp, kp, jnp.zeros((2, 2), jnp.int32),
                               jnp.ones((2,), jnp.int32))


def test_gqa_engine_bitexact_spec_and_share(gqa_model):
    """One greedy batch run is the baseline for BOTH the speculative
    and the prefix-sharing transformer runs — neither may change a
    token."""
    base = prompt(210, 10)
    prompts = [prompt(200, 3), prompt(201, 4),
               base, with_tail(base, [3, 4])]
    mns = [8, 6, 8, 8]
    greedy = decode_all(gqa_model, prompts, mns, ladder=(1, 2))
    eng = make_engine(gqa_model, ladder=(1, 2), spec_k=3,
                      prefix_share=True)
    try:
        streams = [eng.submit(base, max_new=8)]
        for _ in range(4):               # register the donor prefix
            eng.step_once()
            eng.sync()
        streams += [eng.submit(p, max_new=m)
                    for p, m in zip(prompts[:2], mns[:2])]
        streams.append(eng.submit(prompts[3], max_new=8))
        drive(eng)
        got = [s.result(0) for s in streams]
    finally:
        eng.close()
    assert got == [greedy[2], greedy[0], greedy[1], greedy[3]]


def test_gqa_isolated_stream_matches_batched(gqa_model):
    """The continuous-batching invariant carries over to the
    transformer: a request decoded next to batch-mates emits the same
    tokens it emits alone."""
    p = prompt(220, 5)
    alone = decode_all(gqa_model, [p], [9], ladder=(1,))
    crowd = decode_all(gqa_model, [p, prompt(221, 3)], [9, 5],
                       ladder=(1, 2))
    assert crowd[0] == alone[0]
