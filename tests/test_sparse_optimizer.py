"""Row-sparse gradient path: Embedding sparse_grad -> lazy optimizer update
-> kvstore round-trip.

Reference analog: sparse Embedding grad (src/operator/tensor/indexing_op.cc
FInferStorageType row_sparse), lazy updates
(python/mxnet/optimizer/{sgd,adam}.py lazy_update opt-in backed by
src/operator/optimizer_op.cc sparse kernels), kvstore row_sparse push/pull
(src/kvstore/kvstore_dist_server.h:52 kRowSparsePushPull).
"""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, gluon, optimizer as opt
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray.sparse import RowSparseNDArray


def _embed_backward(sparse_grad, ids, vocab=50, dim=4, seed=5):
    onp.random.seed(seed)
    w0 = onp.random.randn(vocab, dim).astype("float32")
    emb = nn.Embedding(vocab, dim, sparse_grad=sparse_grad)
    emb.initialize()
    emb.weight.set_data(nd.array(w0))
    x = nd.array(onp.array(ids, "int32"))
    with autograd.record():
        out = emb(x)
        loss = (out * out).sum()
    loss.backward()
    return emb.weight.grad(), w0


def test_embedding_sparse_grad_structure_and_values():
    ids = [[3, 7, 3], [1, 7, 9]]
    g_sparse, _ = _embed_backward(True, ids)
    g_dense, _ = _embed_backward(False, ids)
    assert isinstance(g_sparse, RowSparseNDArray)
    assert sorted(g_sparse.indices.asnumpy().tolist()) == [1, 3, 7, 9]
    # dense mirror of the sparse grad equals the dense-path grad
    onp.testing.assert_allclose(g_sparse.asnumpy(), g_dense.asnumpy(),
                                rtol=1e-6, atol=1e-6)
    # values rows are the per-unique-id segment sums
    dense = g_dense.asnumpy()
    for i, uid in enumerate(g_sparse.indices.asnumpy()):
        onp.testing.assert_allclose(g_sparse.data.asnumpy()[i], dense[uid],
                                    rtol=1e-6, atol=1e-6)


def test_sgd_lazy_update_touches_only_live_rows():
    vocab, dim = 40, 3
    rng = onp.random.RandomState(0)
    w0 = rng.randn(vocab, dim).astype("float32")
    rows = onp.array([4, 17], "int32")
    vals = rng.randn(2, dim).astype("float32")
    grad = nd.sparse.row_sparse_array((vals, rows), shape=(vocab, dim))

    sgd = opt.SGD(learning_rate=0.1, momentum=0.9, wd=0.01,
                  lazy_update=True)
    assert sgd.lazy_update
    w = nd.array(w0)
    state = sgd.create_state(0, w)
    m0 = onp.asarray(state[0].asnumpy())
    sgd.update(0, w, grad, state)
    w1 = w.asnumpy()
    m1 = state[0].asnumpy()
    untouched = onp.setdiff1d(onp.arange(vocab), rows)
    # untouched rows bitwise identical in BOTH weight and momentum
    onp.testing.assert_array_equal(w1[untouched], w0[untouched])
    onp.testing.assert_array_equal(m1[untouched], m0[untouched])
    # touched rows follow the momentum-SGD rule (wd applied lazily)
    for r, v in zip(rows, vals):
        g = v + 0.01 * w0[r]
        m = 0.9 * 0.0 - 0.1 * g
        onp.testing.assert_allclose(w1[r], w0[r] + m, rtol=1e-5, atol=1e-6)


def test_adam_lazy_update_touches_only_live_rows():
    vocab, dim = 30, 5
    rng = onp.random.RandomState(1)
    w0 = rng.randn(vocab, dim).astype("float32")
    rows = onp.array([0, 29], "int32")
    vals = rng.randn(2, dim).astype("float32")
    grad = nd.sparse.row_sparse_array((vals, rows), shape=(vocab, dim))
    adam = opt.Adam(learning_rate=0.01, lazy_update=True)
    w = nd.array(w0)
    state = adam.create_state(0, w)
    adam.update(0, w, grad, state)
    w1 = w.asnumpy()
    untouched = onp.setdiff1d(onp.arange(vocab), rows)
    onp.testing.assert_array_equal(w1[untouched], w0[untouched])
    for s in state:
        onp.testing.assert_array_equal(s.asnumpy()[untouched],
                                       onp.zeros((len(untouched), dim)))
    # touched rows match the dense Adam result on the same gradient
    adam2 = opt.Adam(learning_rate=0.01, lazy_update=False)
    w_d = nd.array(w0)
    state_d = adam2.create_state(0, w_d)
    adam2.update(0, w_d, nd.array(grad.asnumpy()), state_d)
    onp.testing.assert_allclose(w1[rows], w_d.asnumpy()[rows],
                                rtol=1e-5, atol=1e-6)


def test_non_lazy_sparse_grad_uses_dense_semantics():
    """lazy_update=False with a row_sparse grad must fall back to the dense
    rule (wd decays EVERY row — reference standard update)."""
    vocab, dim = 10, 2
    w0 = onp.ones((vocab, dim), "float32")
    rows = onp.array([2], "int32")
    vals = onp.ones((1, dim), "float32")
    grad = nd.sparse.row_sparse_array((vals, rows), shape=(vocab, dim))
    sgd = opt.SGD(learning_rate=0.1, wd=0.5, lazy_update=False)
    w = nd.array(w0)
    sgd.update(0, w, grad, sgd.create_state(0, w))
    w1 = w.asnumpy()
    # untouched rows still decayed by wd under dense semantics
    onp.testing.assert_allclose(w1[0], w0[0] - 0.1 * (0.5 * w0[0]),
                                rtol=1e-6)


def test_trainer_embedding_sparse_end_to_end():
    """Embedding-heavy step through Trainer + kvstore: loss decreases and
    vocabulary rows never touched by any batch stay bitwise at init."""
    vocab, dim = 100, 8
    onp.random.seed(2)
    net = nn.Sequential()
    emb = nn.Embedding(vocab, dim, sparse_grad=True)
    net.add(emb)
    net.initialize()
    w_init = emb.weight.data().asnumpy().copy()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9,
                             "lazy_update": True},
                            kvstore="tpu")
    used = set()
    losses = []
    for step in range(5):
        ids = onp.random.randint(0, 20, size=(8,))  # only rows 0..19
        used.update(ids.tolist())
        x = nd.array(ids.astype("int32"))
        with autograd.record():
            out = net(x)
            loss = ((out - 1.0) ** 2).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0], losses
    w_now = emb.weight.data().asnumpy()
    untouched = onp.setdiff1d(onp.arange(vocab),
                              onp.array(sorted(used)))
    assert len(untouched) >= 80
    onp.testing.assert_array_equal(w_now[untouched], w_init[untouched])
    touched = onp.array(sorted(used))
    assert (w_now[touched] != w_init[touched]).any()


def test_sparse_grad_lazy_mirror_not_materialized_in_train_step():
    """The O(rows) claim end-to-end: a full backward + Trainer step must
    never materialize the dense (vocab, dim) mirror of the embedding
    gradient; it materializes only when a dense consumer reads it."""
    from mxnet_tpu.ndarray.sparse import LazyRowSparseNDArray
    vocab, dim = 1000, 4
    net = nn.Sequential()
    emb = nn.Embedding(vocab, dim, sparse_grad=True)
    net.add(emb)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "lazy_update": True},
                            kvstore="tpu")
    x = nd.array(onp.array([1, 2, 3], "int32"))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    g = emb.weight.data()._grad
    assert isinstance(g, LazyRowSparseNDArray)
    assert not g.is_materialized
    trainer.step(1)
    assert not g.is_materialized  # whole step stayed on (indices, values)
    # dense read materializes on demand and agrees with the sparse parts
    dense = g.asnumpy()
    assert g.is_materialized
    ids = g.indices.asnumpy()
    onp.testing.assert_allclose(dense[ids], g.data.asnumpy(),
                                rtol=1e-6, atol=1e-6)
    untouched = onp.setdiff1d(onp.arange(vocab), ids)
    assert (dense[untouched] == 0).all()


def test_dense_grad_replaces_stale_sparse_leaf():
    """Tied/shared-weight step: when the accumulated gradient for the
    embedding weight arrives DENSE after a previous sparse step, the leaf's
    old (indices, values) must not survive — the optimizer would re-apply
    last step's rows."""
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    vocab, dim = 20, 2
    emb = nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize()
    w = emb.weight.data()
    # step 1: sparse grad on rows [1, 2]
    with autograd.record():
        loss = (emb(nd.array(onp.array([1, 2], "int32")))).sum()
    loss.backward()
    assert isinstance(w._grad, RowSparseNDArray)
    # step 2: the weight participates TWICE (sparse lookup + dense use) so
    # cotangents accumulate to a dense gradient on different rows
    with autograd.record():
        out = emb(nd.array(onp.array([5, 6], "int32"))).sum() \
            + (emb.weight.data() * 0.5).sum()
        loss2 = out
    loss2.backward()
    g2 = w._grad
    assert not isinstance(g2, RowSparseNDArray)  # replaced, aux gone
    dense = g2.asnumpy()
    onp.testing.assert_allclose(dense[5], [1.5, 1.5])
    onp.testing.assert_allclose(dense[0], [0.5, 0.5])


def test_sparse_update_bucketed_compiles():
    """Variable unique-token counts share compiled programs: the row count
    pads to the next power of two before the jitted sparse step."""
    vocab, dim = 64, 2
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9, lazy_update=True)
    w = nd.array(onp.zeros((vocab, dim), "float32"))
    state = sgd.create_state(0, w)
    for n in (3, 4, 5, 7):   # all bucket to 4 or 8
        rows = onp.arange(n, dtype="int32")
        vals = onp.ones((n, dim), "float32")
        g = nd.sparse.row_sparse_array((vals, rows), shape=(vocab, dim))
        sgd.update(0, w, g, state)
    # buckets {4, 8}: exactly two distinct signatures (trace-time set —
    # stable under jit-cache eviction/retraces, unlike _cache_size)
    assert sgd._sparse_trace_buckets == {4, 8}
    # padding rows are dropped: row `vocab-1` was never touched
    assert w.asnumpy()[vocab - 1].tolist() == [0.0, 0.0]


def test_all_rows_sparse_grad_falls_back_to_dense_rule():
    vocab, dim = 8, 2
    g = nd.sparse.row_sparse_array(
        (onp.ones((vocab, dim), "float32"),
         onp.arange(vocab, dtype="int32")), shape=(vocab, dim))
    sgd = opt.SGD(learning_rate=0.1)
    w = nd.array(onp.zeros((vocab, dim), "float32"))
    sgd.update(0, w, g, sgd.create_state(0, w))
    onp.testing.assert_allclose(w.asnumpy(), -0.1 * onp.ones((vocab, dim)),
                                rtol=1e-6)


def test_kvstore_row_sparse_pull_and_aux_consistency():
    store = mx.kvstore.create("tpu")
    vocab, dim = 12, 3
    w = nd.array(onp.arange(vocab * dim, dtype="float32").reshape(vocab, dim))
    store.init("emb", w)
    out = nd.zeros((vocab, dim))
    store.row_sparse_pull("emb", out=out, row_ids=nd.array([1, 5]))
    got = out.asnumpy()
    expect = onp.zeros((vocab, dim), "float32")
    expect[[1, 5]] = w.asnumpy()[[1, 5]]
    onp.testing.assert_allclose(got, expect)
    # pushpull with a single row_sparse grad keeps (indices, values) usable
    grad = nd.sparse.row_sparse_array(
        (onp.ones((2, dim), "float32"), onp.array([0, 3], "int32")),
        shape=(vocab, dim))
    store.pushpull("emb_g", grad)
    assert isinstance(grad, RowSparseNDArray)
    assert sorted(grad.indices.asnumpy().tolist()) == [0, 3]
