"""gluon.probability + estimator tests.

Numerics oracle: scipy.stats log-pdfs (reference test style:
tests/python/unittest/test_gluon_probability_v2.py compares vs scipy).
"""
import numpy as onp
import pytest
import scipy.stats as ss

import mxnet_tpu as mx
from mxnet_tpu.gluon import probability as mgp
from mxnet_tpu.base import MXNetError


def _lp(dist, v):
    return dist.log_prob(mx.nd.array(onp.asarray(v, "float32"))).asnumpy()


@pytest.mark.parametrize("case", [
    ("Normal", lambda: mgp.Normal(1.0, 2.0),
     lambda v: ss.norm.logpdf(v, 1.0, 2.0), onp.linspace(-3, 3, 7)),
    ("LogNormal", lambda: mgp.LogNormal(0.5, 0.8),
     lambda v: ss.lognorm.logpdf(v, 0.8, scale=onp.exp(0.5)),
     onp.linspace(0.2, 4, 6)),
    ("Laplace", lambda: mgp.Laplace(0.0, 1.5),
     lambda v: ss.laplace.logpdf(v, 0, 1.5), onp.linspace(-2, 2, 5)),
    ("Cauchy", lambda: mgp.Cauchy(0.5, 1.0),
     lambda v: ss.cauchy.logpdf(v, 0.5, 1.0), onp.linspace(-2, 2, 5)),
    ("Gumbel", lambda: mgp.Gumbel(0.0, 2.0),
     lambda v: ss.gumbel_r.logpdf(v, 0, 2.0), onp.linspace(-2, 4, 5)),
    ("Exponential", lambda: mgp.Exponential(2.0),
     lambda v: ss.expon.logpdf(v, scale=2.0), onp.linspace(0.1, 5, 5)),
    ("Gamma", lambda: mgp.Gamma(3.0, 2.0),
     lambda v: ss.gamma.logpdf(v, 3.0, scale=2.0), onp.linspace(0.5, 8, 5)),
    ("Beta", lambda: mgp.Beta(2.0, 3.0),
     lambda v: ss.beta.logpdf(v, 2.0, 3.0), onp.linspace(0.1, 0.9, 5)),
    ("Chi2", lambda: mgp.Chi2(4.0),
     lambda v: ss.chi2.logpdf(v, 4.0), onp.linspace(0.5, 9, 5)),
    ("StudentT", lambda: mgp.StudentT(5.0, 0.0, 1.0),
     lambda v: ss.t.logpdf(v, 5.0), onp.linspace(-2, 2, 5)),
    ("Weibull", lambda: mgp.Weibull(1.5, 2.0),
     lambda v: ss.weibull_min.logpdf(v, 1.5, scale=2.0),
     onp.linspace(0.3, 4, 5)),
    ("Pareto", lambda: mgp.Pareto(3.0, 1.0),
     lambda v: ss.pareto.logpdf(v, 3.0), onp.linspace(1.1, 4, 5)),
    ("Poisson", lambda: mgp.Poisson(3.0),
     lambda v: ss.poisson.logpmf(v, 3.0), onp.arange(0, 8.0)),
    ("Geometric", lambda: mgp.Geometric(0.3),
     lambda v: ss.geom.logpmf(v + 1, 0.3), onp.arange(0, 6.0)),
    ("HalfNormal", lambda: mgp.HalfNormal(2.0),
     lambda v: ss.halfnorm.logpdf(v, scale=2.0), onp.linspace(0.1, 4, 5)),
    ("HalfCauchy", lambda: mgp.HalfCauchy(1.0),
     lambda v: ss.halfcauchy.logpdf(v), onp.linspace(0.1, 4, 5)),
    ("Uniform", lambda: mgp.Uniform(-1.0, 3.0),
     lambda v: ss.uniform.logpdf(v, -1.0, 4.0), onp.linspace(-0.5, 2.5, 5)),
], ids=lambda c: c[0] if isinstance(c, tuple) else str(c))
def test_log_prob_vs_scipy(case):
    _, mk, ref_fn, grid = case
    d = mk()
    onp.testing.assert_allclose(_lp(d, grid), ref_fn(grid),
                                rtol=2e-5, atol=2e-5)


def test_bernoulli_and_categorical():
    b = mgp.Bernoulli(prob=0.3)
    onp.testing.assert_allclose(
        _lp(b, [0.0, 1.0]), ss.bernoulli.logpmf([0, 1], 0.3), rtol=1e-6)
    logit = onp.log(onp.array([0.2, 0.3, 0.5], "float32"))
    c = mgp.Categorical(logit=mx.nd.array(logit))
    onp.testing.assert_allclose(
        _lp(c, [0.0, 1.0, 2.0]), onp.log([0.2, 0.3, 0.5]), rtol=1e-5)
    ent = c.entropy().asnumpy()
    onp.testing.assert_allclose(ent, ss.entropy([0.2, 0.3, 0.5]), rtol=1e-5)


def test_dirichlet_mvn():
    alpha = onp.array([2.0, 3.0, 4.0], "float32")
    d = mgp.Dirichlet(mx.nd.array(alpha))
    v = onp.array([0.2, 0.3, 0.5], "float32")
    onp.testing.assert_allclose(_lp(d, v), ss.dirichlet.logpdf(v, alpha),
                                rtol=1e-5)
    cov = onp.array([[2.0, 0.3], [0.3, 1.0]], "float32")
    mvn = mgp.MultivariateNormal(mx.nd.array(onp.zeros(2, "float32")),
                                 cov=mx.nd.array(cov))
    v2 = onp.array([0.5, -0.7], "float32")
    onp.testing.assert_allclose(
        _lp(mvn, v2), ss.multivariate_normal.logpdf(v2, onp.zeros(2), cov),
        rtol=1e-5)


def test_sampling_moments():
    mx.random.seed(7)
    n = mgp.Normal(2.0, 0.5)
    s = n.sample((20000,)).asnumpy()
    assert abs(s.mean() - 2.0) < 0.02 and abs(s.std() - 0.5) < 0.02
    g = mgp.Gamma(3.0, 2.0)
    sg = g.sample((20000,)).asnumpy()
    assert abs(sg.mean() - 6.0) < 0.15
    c = mgp.Categorical(logit=mx.nd.array(onp.log([0.1, 0.9]).astype("float32")))
    sc = c.sample((5000,)).asnumpy()
    assert abs(sc.mean() - 0.9) < 0.05


def test_kl_registry():
    p, q = mgp.Normal(0.0, 1.0), mgp.Normal(1.0, 2.0)
    kl = mgp.kl_divergence(p, q).asnumpy()
    expected = onp.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    onp.testing.assert_allclose(kl, expected, rtol=1e-5)
    with pytest.raises(MXNetError, match="no KL registered"):
        mgp.kl_divergence(mgp.Normal(0, 1), mgp.Gamma(1.0, 1.0))


def test_log_prob_differentiable():
    loc = mx.nd.array(onp.array([0.5], "float32"))
    loc.attach_grad()
    with mx.autograd.record():
        d_lp = mgp.Normal(loc, mx.nd.array(onp.array([1.0], "float32")))
        lp = d_lp.log_prob(mx.nd.array(onp.array([2.0], "float32"))).sum()
    lp.backward()
    onp.testing.assert_allclose(loc.grad.asnumpy(), [1.5], rtol=1e-5)


def test_stochastic_block_collects_losses():
    from mxnet_tpu.gluon.probability import StochasticBlock
    from mxnet_tpu.gluon import nn

    class VAELayer(StochasticBlock):
        def __init__(self):
            super().__init__()
            self.dense = nn.Dense(4, in_units=4)

        @StochasticBlock.collectLoss
        def forward(self, x):
            out = self.dense(x)
            self.add_loss((out * out).mean())
            return out

    blk = VAELayer()
    blk.initialize()
    out = blk(mx.nd.ones((2, 4)))
    assert out.shape == (2, 4)
    assert len(blk.losses) == 1


def test_estimator_fit_and_handlers(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import (Estimator,
                                                   CheckpointHandler,
                                                   EarlyStoppingHandler)
    from mxnet_tpu.gluon import nn, data as gdata
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    rng = onp.random.RandomState(0)
    x = rng.randn(64, 8).astype("float32")
    w = rng.randn(8, 3).astype("float32")
    y = x.dot(w).argmax(1).astype("int32")
    ds = gdata.ArrayDataset(mx.nd.array(x), mx.nd.array(y))
    loader = gdata.DataLoader(ds, batch_size=16, shuffle=True)

    net = nn.Dense(3, in_units=8)
    net.initialize()
    est = Estimator(net, SoftmaxCrossEntropyLoss(),
                    trainer=mx.gluon.Trainer(net.collect_params(), "adam",
                                             {"learning_rate": 0.05}))
    ckpt = CheckpointHandler(str(tmp_path), monitor=est.train_loss_metric,
                             save_best=True)
    early = EarlyStoppingHandler(monitor=est.train_loss_metric, patience=50)
    est.fit(loader, epochs=5, event_handlers=[ckpt, early])
    name, acc = est.train_metrics[0].get()
    assert acc > 0.5, (name, acc)
    import os
    assert any(f.endswith(".params") for f in os.listdir(tmp_path))
