"""Minimal 2-process smoke worker: protects jax.distributed CPU bring-up
(the dependency every dist kvstore feature rides) inside the QUICK gate —
tiny arrays, two collectives, done. The full feature matrix lives in
dist_kvstore_worker.py (slow suite).

Capability note: some jaxlib builds cannot RUN multi-process collectives
on the CPU backend at all ("Multiprocess computations aren't implemented
on the CPU backend").  That is a backend capability, not a framework
regression — launch + jax.distributed.initialize + kvstore construction
(the things a jax/jaxlib bump actually breaks) still execute here, and
the worker records ``{"capability": "no-cpu-multiprocess"}`` so the test
can skip the collective assertions with a documented reason instead of
failing the quick gate."""
import json
import os
import sys

os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count"))
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as onp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu.parallel import dist  # noqa: E402


def _write(outdir, rank, payload):
    with open(os.path.join(outdir, f"smoke{rank}.json"), "w") as f:
        json.dump(payload, f)


def main(outdir):
    dist.initialize()
    rank = jax.process_index()
    kv = mx.kvstore.create("dist_sync")
    g = nd.array(onp.full((3,), float(rank + 1), "float32"))
    try:
        kv.pushpull("g", g)
        g.wait_to_read()
    except Exception as e:
        if "aren't implemented on the CPU backend" in str(e):
            # init + store construction proven; the backend simply has
            # no CPU multi-process collective runtime
            _write(outdir, rank, {"rank": rank,
                                  "capability": "no-cpu-multiprocess",
                                  "error": str(e)[:300]})
            return
        raise
    a = nd.array(onp.full((2,), float(rank + 1), "float32"))
    b = nd.array(onp.full((5,), 2.0 * (rank + 1), "float32"))
    kv.pushpull_list([0, 1], [a, b])
    out = {"rank": rank, "sum": g.asnumpy().tolist(),
           "fused": [a.asnumpy().tolist(), b.asnumpy().tolist()],
           "stats": dict(kv.stats)}
    _write(outdir, rank, out)


if __name__ == "__main__":
    main(sys.argv[1])
