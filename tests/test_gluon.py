"""Gluon Block/HybridBlock/layer tests (reference:
tests/python/unittest/test_gluon.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import nn


def test_dense_shapes_and_values():
    layer = nn.Dense(5, in_units=3, use_bias=True)
    layer.initialize()
    x = nd.ones((2, 3))
    y = layer(x)
    assert y.shape == (2, 5)
    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy()
    onp.testing.assert_allclose(y.asnumpy(), x.asnumpy() @ w.T + b, rtol=1e-5)


def test_dense_deferred_init():
    layer = nn.Dense(4)
    layer.initialize()
    assert layer.weight.shape == (4, 0)
    y = layer(nd.ones((2, 7)))
    assert layer.weight.shape == (4, 7)
    assert y.shape == (2, 4)


def test_dense_flatten():
    layer = nn.Dense(4, flatten=True)
    layer.initialize()
    assert layer(nd.ones((2, 3, 5))).shape == (2, 4)
    layer2 = nn.Dense(4, flatten=False)
    layer2.initialize()
    assert layer2(nd.ones((2, 3, 5))).shape == (2, 3, 4)


def test_sequential_and_children():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    assert len(net) == 2
    y = net(nd.ones((4, 16)))
    assert y.shape == (4, 2)
    params = net.collect_params()
    assert len(params) == 4  # 2x weight+bias
    assert any("weight" in k for k in params)


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="tanh"), nn.Dense(3))
    net.initialize()
    x = nd.random.uniform(shape=(5, 8))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    onp.testing.assert_allclose(y_eager, y_hybrid, rtol=1e-5, atol=1e-6)


def test_hybridize_gradients_match():
    net = nn.Dense(3, in_units=4)
    net.initialize()
    x = nd.random.uniform(shape=(2, 4))
    with autograd.record():
        l1 = (net(x) ** 2).sum()
    l1.backward()
    g1 = net.weight.grad().asnumpy().copy()
    net.weight.zero_grad()
    net.hybridize()
    with autograd.record():
        l2 = (net(x) ** 2).sum()
    l2.backward()
    g2 = net.weight.grad().asnumpy()
    onp.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-6)


def test_conv2d():
    layer = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    layer.initialize()
    x = nd.random.uniform(shape=(2, 3, 16, 16))
    y = layer(x)
    assert y.shape == (2, 8, 16, 16)
    # stride 2
    layer2 = nn.Conv2D(4, kernel_size=3, strides=2, padding=1)
    layer2.initialize()
    assert layer2(x).shape == (2, 4, 8, 8)


def test_conv2d_groups():
    layer = nn.Conv2D(8, kernel_size=1, groups=2, in_channels=4)
    layer.initialize()
    assert layer.weight.shape == (8, 2, 1, 1)
    y = layer(nd.ones((1, 4, 5, 5)))
    assert y.shape == (1, 8, 5, 5)


def test_conv_transpose():
    layer = nn.Conv2DTranspose(3, kernel_size=4, strides=2, padding=1,
                               in_channels=6)
    layer.initialize()
    x = nd.random.uniform(shape=(2, 6, 8, 8))
    y = layer(x)
    assert y.shape == (2, 3, 16, 16)


def test_pooling():
    x = nd.array(onp.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    mp = nn.MaxPool2D(pool_size=2)
    assert mp(x).shape == (1, 1, 2, 2)
    onp.testing.assert_allclose(mp(x).asnumpy()[0, 0], [[5, 7], [13, 15]])
    ap = nn.AvgPool2D(pool_size=2)
    onp.testing.assert_allclose(ap(x).asnumpy()[0, 0], [[2.5, 4.5],
                                                        [10.5, 12.5]])
    gp = nn.GlobalAvgPool2D()
    assert gp(x).shape == (1, 1, 1, 1)
    assert float(gp(x).asnumpy()) == 7.5


def test_batchnorm_train_and_infer():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = nd.random.normal(2.0, 3.0, shape=(32, 3, 4, 4))
    with autograd.record():
        y = bn(x)
    # normalized output should have ~0 mean ~1 std per channel
    yn = y.asnumpy()
    assert abs(yn.mean()) < 0.1
    assert abs(yn.std() - 1.0) < 0.1
    # running stats moved toward batch stats
    rm = bn.running_mean.data().asnumpy()
    assert abs(rm.mean() - 0.2) < 0.15  # 0.1 * batch_mean(≈2)
    # inference uses running stats
    y2 = bn(x)
    assert not onp.allclose(y2.asnumpy(), yn)


def test_layernorm_groupnorm():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = nd.random.uniform(shape=(4, 6))
    y = ln(x).asnumpy()
    onp.testing.assert_allclose(y.mean(axis=-1), onp.zeros(4), atol=1e-5)
    gn = nn.GroupNorm(num_groups=2, in_channels=4)
    gn.initialize()
    assert gn(nd.random.uniform(shape=(2, 4, 3, 3))).shape == (2, 4, 3, 3)


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = nd.array([1, 3, 5])
    y = emb(idx)
    assert y.shape == (3, 4)
    onp.testing.assert_allclose(y.asnumpy(),
                                emb.weight.data().asnumpy()[[1, 3, 5]])


def test_activations():
    x = nd.array([-2.0, -0.5, 0.5, 2.0])
    assert nn.Activation("relu")(x).asnumpy().tolist() == [0, 0, 0.5, 2.0]
    lrelu = nn.LeakyReLU(0.1)(x).asnumpy()
    onp.testing.assert_allclose(lrelu, [-0.2, -0.05, 0.5, 2.0], rtol=1e-6)
    selu = nn.SELU()(x)
    swish = nn.Swish()(x)
    elu = nn.ELU()(x)
    gelu = nn.GELU()(x)
    assert selu.shape == swish.shape == elu.shape == gelu.shape == (4,)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net2.load_parameters(f)
    x = nd.random.uniform(shape=(2, 4))
    onp.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy(),
                                rtol=1e-6)


def test_losses():
    from mxnet_tpu.gluon import loss as gloss
    pred = nd.array([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0]])
    label = nd.array([2, 0])
    l = gloss.SoftmaxCrossEntropyLoss()(pred, label)
    expected = -onp.log(onp.exp([3.0, 3.0]) /
                        onp.exp([[1, 2, 3], [3, 2, 1]]).sum(axis=1))
    onp.testing.assert_allclose(l.asnumpy(), expected, rtol=1e-5)

    l2 = gloss.L2Loss()(nd.array([1.0, 2.0]), nd.array([0.0, 0.0]))
    onp.testing.assert_allclose(l2.asnumpy(), [0.5, 2.0])

    l1 = gloss.L1Loss()(nd.array([1.0, -2.0]), nd.array([0.0, 0.0]))
    onp.testing.assert_allclose(l1.asnumpy(), [1.0, 2.0])

    bce = gloss.SigmoidBCELoss()(nd.array([0.0]), nd.array([1.0]))
    onp.testing.assert_allclose(bce.asnumpy(), [onp.log(2)], rtol=1e-5)

    huber = gloss.HuberLoss()(nd.array([0.5, 3.0]), nd.array([0.0, 0.0]))
    onp.testing.assert_allclose(huber.asnumpy(), [0.125, 2.5], rtol=1e-5)

    kl = gloss.KLDivLoss()(nd.log(nd.array([[0.5, 0.5]])),
                           nd.array([[0.5, 0.5]]))
    assert abs(float(kl.asnumpy())) < 1e-6


def test_loss_backward():
    from mxnet_tpu.gluon import loss as gloss
    net = nn.Dense(3, in_units=5)
    net.initialize()
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    x = nd.random.uniform(shape=(4, 5))
    y = nd.array([0, 1, 2, 0])
    with autograd.record():
        l = loss_fn(net(x), y).mean()
    l.backward()
    g = net.weight.grad().asnumpy()
    assert onp.abs(g).sum() > 0


def test_metrics():
    from mxnet_tpu import metric
    acc = metric.Accuracy()
    acc.update(nd.array([0, 1, 1]), nd.array([[0.9, 0.1], [0.2, 0.8],
                                              [0.7, 0.3]]))
    assert acc.get() == ("accuracy", 2.0 / 3.0)
    mae = metric.MAE()
    mae.update(nd.array([1.0, 2.0]), nd.array([1.5, 2.5]))
    assert abs(mae.get()[1] - 0.5) < 1e-6
    comp = metric.CompositeEvalMetric(["acc", "mse"])
    assert len(comp.metrics) == 2
    topk = metric.TopKAccuracy(top_k=2)
    topk.update(nd.array([2]), nd.array([[0.3, 0.1, 0.2]]))
    assert topk.get()[1] == 1.0


def test_pcc_metric():
    """PCC = multiclass MCC over a confusion matrix (reference
    gluon/metric.py:1586). For binary inputs it must equal MCC, and its
    confusion matrix must grow when higher class indices appear."""
    from mxnet_tpu import metric
    labels = nd.array([0] * 1001 + [1] * 10001)
    preds = nd.array([[0.3, 0.7]] * 1000 + [[0.7, 0.3]] * 2
                     + [[0.3, 0.7]] * 10000)
    pcc = metric.PCC()
    pcc.update([labels], [preds])
    mcc = metric.MCC()
    mcc.update([labels], [preds])
    assert abs(pcc.get()[1] - mcc.get()[1]) < 1e-9
    # growing: feed 4-class predictions into the same metric
    pcc.update([nd.array([3, 2, 1, 0])],
               [nd.array([3, 2, 1, 0])])
    assert pcc.k == 4
    # perfect extra batch only raises correlation
    assert pcc.get()[1] > mcc.get()[1]
    # registry + np() helper
    assert isinstance(metric.create("pcc"), metric.PCC)
    m = metric.np(lambda l, p: float((l == p).sum()) / l.size, name="frac")
    m.update([nd.array([1, 1])], [nd.array([1, 0])])
    assert m.get()[1] == 0.5


def test_dropout_layer_modes():
    drop = nn.Dropout(0.5)
    x = nd.ones((100,))
    # inference: identity
    onp.testing.assert_allclose(drop(x).asnumpy(), x.asnumpy())
    with autograd.record():
        y = drop(x)
    zeros = int((y.asnumpy() == 0).sum())
    assert 10 < zeros < 90


def test_hybridize_dropout_varies_between_calls():
    drop = nn.Dropout(0.5)
    drop.hybridize()
    x = nd.ones((256,))
    with autograd.record():
        y1 = drop(x).asnumpy()
        y2 = drop(x).asnumpy()
    assert (y1 != y2).any()


def test_initializers():
    from mxnet_tpu import initializer as init
    net = nn.Dense(16, in_units=64)
    net.initialize(init=init.Xavier())
    w = net.weight.data().asnumpy()
    bound = onp.sqrt(3.0 / ((16 + 64) / 2))
    assert w.min() >= -bound and w.max() <= bound
    net2 = nn.Dense(4, in_units=4)
    net2.initialize(init=init.Constant(0.5))
    onp.testing.assert_allclose(net2.weight.data().asnumpy(),
                                onp.full((4, 4), 0.5))
    # bias always zero-initialized
    onp.testing.assert_allclose(net2.bias.data().asnumpy(), onp.zeros(4))


def test_block_repr_and_apply():
    net = nn.HybridSequential()
    net.add(nn.Dense(2, in_units=2))
    net.initialize()
    seen = []
    net.apply(lambda b: seen.append(type(b).__name__))
    assert "Dense" in seen and "HybridSequential" in seen


def test_avg_pool_ceil_mode_denominator():
    """ceil_mode extra padding must not count toward the avg denominator
    (reference src/operator/nn/pool.h clips the window)."""
    import numpy as onp
    from mxnet_tpu import nd
    x = nd.array(onp.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    from mxnet_tpu.ndarray import nn_ops as FNN
    y = FNN.Pooling(x, kernel=(3, 3), pool_type="avg", stride=(2, 2),
                    ceil_mode=True).asnumpy()
    assert y.shape == (1, 1, 2, 2)
    xn = onp.arange(16, dtype="float32").reshape(4, 4)
    # window [2:4, 2:4] has only 4 real elements -> mean over 4, not 9
    onp.testing.assert_allclose(y[0, 0, 1, 1], xn[2:4, 2:4].mean(), rtol=1e-6)
    onp.testing.assert_allclose(y[0, 0, 0, 0], xn[0:3, 0:3].mean(), rtol=1e-6)


def test_trainer_stale_grad_skips_param():
    """With ignore_stale_grad=True the stale parameter is skipped, not
    re-updated with the old gradient (reference trainer.py behavior)."""
    import numpy as onp
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer, nn
    a = nn.Dense(1, in_units=2, use_bias=False)
    b = nn.Dense(1, in_units=2, use_bias=False)
    a.initialize()
    b.initialize()
    params = {**{f"a.{k}": v for k, v in a.collect_params().items()},
              **{f"b.{k}": v for k, v in b.collect_params().items()}}
    trainer = Trainer(params, "sgd", {"learning_rate": 0.1})
    x = nd.ones((2, 2))
    with autograd.record():
        loss = (a(x) + b(x)).sum()
    loss.backward()
    trainer.step(1)
    b0 = b.weight.data().asnumpy().copy()
    with autograd.record():
        loss = a(x).sum()   # b unused this iteration
    loss.backward()
    trainer.step(1, ignore_stale_grad=True)
    onp.testing.assert_allclose(b.weight.data().asnumpy(), b0)


def test_updater_states_keep_update_counts(tmp_path):
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer, nn
    net = nn.Dense(1, in_units=1)
    net.initialize()
    trainer = Trainer(net.collect_params(), "adam")
    x = nd.ones((1, 1))
    for _ in range(5):
        with autograd.record():
            l = net(x).sum()
        l.backward()
        trainer.step(1)
    f = str(tmp_path / "s.states")
    trainer.save_states(f)
    trainer2 = Trainer(net.collect_params(), "adam")
    trainer2.load_states(f)
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update


def test_chained_hybridized_blocks_backprop():
    """Regression: a hybridized block consuming another cached op's output
    must keep the tape chain — args are flattened with NDArray as leaf in
    _call_cached_op so upstream _tape_entry handles survive."""
    import numpy as onp
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import nn
    d0, d1 = nn.Dense(16), nn.Dense(10)
    d0.initialize(); d1.initialize()
    d0.hybridize(); d1.hybridize()
    x = nd.array(onp.random.rand(8, 20).astype("float32"))
    with autograd.record():
        loss = (d1(d0(x)) ** 2).mean()
    loss.backward()
    for p in list(d0.collect_params().values()) + \
            list(d1.collect_params().values()):
        assert p.data().fresh_grad, p.name
        assert float(abs(p.grad().asnumpy()).max()) > 0, p.name


def test_sequential_hybridize_matches_eager_training():
    """Eager and hybridized training must produce identical loss curves
    when starting from identical parameters."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    def run(hybrid):
        mx.random.seed(7)
        onp.random.seed(7)
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(10))
        net.initialize()
        X = mx.nd.array(onp.random.rand(16, 64).astype("float32"))
        Y = mx.nd.array(onp.random.randint(0, 10, 16).astype("int32"))
        net(X)  # complete deferred init identically in both runs
        if hybrid:
            net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore="tpu")
        lf = gluon.loss.SoftmaxCrossEntropyLoss()
        losses = []
        for _ in range(5):
            with autograd.record():
                l = lf(net(X), Y)
            l.backward()
            tr.step(16)
            losses.append(float(l.mean().asnumpy()))
        return losses

    le, lh = run(False), run(True)
    assert le[-1] < le[0]
    assert max(abs(a - b) for a, b in zip(le, lh)) < 1e-4, (le, lh)


def test_hybridize_kwargs_and_static_flags():
    """Hybridized forward accepts keyword tensors (traced, grads flow) and
    python scalar flags (static — branching in forward works per signature)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import HybridBlock

    class Flagged(HybridBlock):
        def forward(self, x, double=False, bias=None):
            if double:
                x = x * 2
            if bias is not None:
                x = x + bias
            return x

    m = Flagged()
    m.initialize()
    m.hybridize()
    x = mx.nd.ones((2, 3))
    assert float(m(x).asnumpy()[0, 0]) == 1.0
    assert float(m(x, double=True).asnumpy()[0, 0]) == 2.0
    assert float(m(x, True, bias=mx.nd.ones((2, 3))).asnumpy()[0, 0]) == 3.0
    b = mx.nd.ones((2, 3))
    b.attach_grad()
    with autograd.record():
        loss = m(x, double=True, bias=b).sum()
    loss.backward()
    assert float(b.grad.asnumpy().sum()) == 6.0


def test_cachedop_shape_bucketing():
    """Retrace policy (reference dynamic CachedOp, cached_op.cc:696):
    bucket_axis pads variable lengths to the next bucket so two bucketable
    lengths share ONE compiled entry; outputs slice back to the true length
    and gradients flow through the pad."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn

    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize(bucket_axis=0)
    eager = nn.Dense(4, in_units=3)
    eager.initialize()
    eager.weight.set_data(net.weight.data())
    eager.bias.set_data(net.bias.data())

    x5 = mx.nd.array(onp.random.randn(5, 3).astype("float32"))
    x7 = mx.nd.array(onp.random.randn(7, 3).astype("float32"))
    y5 = net(x5)
    y7 = net(x7)
    assert y5.shape == (5, 4) and y7.shape == (7, 4)
    onp.testing.assert_allclose(y5.asnumpy(), eager(x5).asnumpy(),
                                rtol=2e-6, atol=2e-6)
    onp.testing.assert_allclose(y7.asnumpy(), eager(x7).asnumpy(),
                                rtol=2e-6, atol=2e-6)
    # both lengths pad to bucket 8 -> a single compiled signature
    # (trace-time record — stable under jit-cache eviction, unlike
    # _cache_size introspection)
    assert len(net._trace_signatures) == 1
    # a non-bucketable length compiles a second entry
    net(mx.nd.ones((9, 3)))
    assert len(net._trace_signatures) == 2

    # gradients flow back through the pad/slice pair
    x5.attach_grad()
    with autograd.record():
        loss = net(x5).sum()
    loss.backward()
    eager_x = mx.nd.array(x5.asnumpy())
    eager_x.attach_grad()
    with autograd.record():
        loss2 = eager(eager_x).sum()
    loss2.backward()
    onp.testing.assert_allclose(x5.grad.asnumpy(), eager_x.grad.asnumpy(),
                                rtol=2e-6, atol=2e-6)


def test_bucket_unpad_exact_shapes_not_coincidence():
    """Unpadding uses true output shapes from an abstract trace at the
    original length: an output whose dim coincidentally equals the bucket
    size (64 classes vs bucket 64) must NOT be sliced, while an output that
    really carries the padded length is."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import HybridBlock

    class M(HybridBlock):
        def forward(self, x):
            logits = mx.nd.dot(x[:, 0:1], mx.nd.ones((1, 64)))  # (B, 64)
            seq = x * 2                                         # (B, L)
            return logits, seq

    m = M()
    m.initialize()
    m.hybridize(bucket_axis=1)
    x = mx.nd.array(onp.arange(2 * 48, dtype="float32").reshape(2, 48))
    logits, seq = m(x)
    assert logits.shape == (2, 64), logits.shape   # untouched coincidence
    assert seq.shape == (2, 48), seq.shape          # padded length sliced
    onp.testing.assert_allclose(seq.asnumpy(), x.asnumpy() * 2)
    onp.testing.assert_allclose(
        logits.asnumpy(), onp.tile(x.asnumpy()[:, 0:1], (1, 64)))


def test_cachedop_explicit_bucket_sizes_and_lru(monkeypatch):
    """bucket_sizes pins the bucket ladder; MXNET_CACHEDOP_CACHE_SIZE caps
    live compiled signatures with LRU eviction."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    monkeypatch.setenv("MXNET_CACHEDOP_CACHE_SIZE", "1")
    net = nn.Dense(2, in_units=3)
    net.initialize()
    net.hybridize(bucket_axis=0, bucket_sizes=[4, 16])
    net(mx.nd.ones((3, 3)))   # -> bucket 4
    net(mx.nd.ones((4, 3)))   # -> bucket 4, same entry
    assert len(net._jit_lru) == 1
    net(mx.nd.ones((10, 3)))  # -> bucket 16, evicts bucket-4 entry
    assert len(net._jit_lru) == 1
    out = net(mx.nd.ones((5, 3)))  # recompiles bucket 4 after eviction
    assert out.shape == (5, 2)
    assert len(net._jit_lru) == 1


def test_threadsafe_cachedop_concurrent_inference():
    """Reference thread-safe CachedOp (src/imperative/cached_op_threadsafe.cc,
    example/multi_threaded_inference): concurrent forward calls on ONE
    hybridized net from many threads must all produce the single-thread
    result. jit dispatch is thread-safe by construction — this pins the
    claim with a real multithreaded run."""
    import threading
    import queue
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    net.hybridize()
    xs = [mx.nd.array(onp.random.RandomState(i).randn(4, 16)
                      .astype("float32")) for i in range(8)]
    expected = [net(x).asnumpy() for x in xs]  # also compiles once

    errors: queue.Queue = queue.Queue()

    def worker(idx):
        try:
            for _ in range(5):
                out = net(xs[idx]).asnumpy()
                onp.testing.assert_allclose(out, expected[idx],
                                            rtol=1e-6, atol=1e-6)
        except Exception as e:  # pragma: no cover - failure path
            errors.put((idx, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors.empty(), list(errors.queue)


def test_optimize_for_backends():
    """Subgraph backends (reference optimize_for/SubgraphProperty):
    remat + bf16 transforms of the hybridized computation."""
    import mxnet_tpu.subgraph as sg
    assert "remat" in sg.list_backends() and "bf16" in sg.list_backends()
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randn(4, 8).astype("float32"))

    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
        net.initialize()
        return net

    mx.random.seed(3)
    base = build()
    ref = base(x).asnumpy()

    mx.random.seed(3)
    net_r = build()
    out_r = net_r.optimize_for(x, backend="remat")
    onp.testing.assert_allclose(out_r.asnumpy(), ref, rtol=1e-5, atol=1e-6)
    # grads flow through the remat'd program
    with mx.autograd.record():
        loss = (net_r(x) ** 2).sum()
    loss.backward()
    g = [p.grad() for p in net_r.collect_params().values()]
    assert any(float(onp.abs(a.asnumpy()).sum()) > 0 for a in g)

    mx.random.seed(3)
    net_b = build()
    out_b = net_b.optimize_for(x, backend="bf16")
    assert str(out_b.dtype) == "float32"
    onp.testing.assert_allclose(out_b.asnumpy(), ref, rtol=0.05, atol=0.05)
    assert not onp.array_equal(out_b.asnumpy(), ref)  # really ran in bf16

    from mxnet_tpu.base import MXNetError as _E
    try:
        build().optimize_for(x, backend="nope")
        assert False, "expected error"
    except _E as e:
        assert "not registered" in str(e)


def test_cold_hybridize_same_seed_same_weights():
    """Deferred init under a cold hybridized first call must draw the same
    RNG sequence as eager execution (regression: child cached-ops consumed
    per-call keys between inits, so `1.weight` diverged; the reference
    guarantees init is independent of hybridize())."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn as gnn

    def build():
        mx.random.seed(7)
        net = gnn.HybridSequential()
        net.add(gnn.Dense(16, activation="relu"), gnn.Dense(4))
        net.initialize(init=mx.init.Xavier())
        return net

    x = mx.nd.array(onp.random.RandomState(1).randn(4, 8).astype("float32"))
    n1 = build()
    o1 = n1(x).asnumpy()
    n2 = build()
    n2.hybridize()
    o2 = n2(x).asnumpy()
    onp.testing.assert_allclose(o1, o2, atol=1e-6)
    for k in n1.collect_params():
        onp.testing.assert_allclose(
            n1.collect_params()[k].data().asnumpy(),
            n2.collect_params()[k].data().asnumpy(),
            err_msg=f"param {k} diverged under cold hybridize")
