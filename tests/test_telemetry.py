"""Unified runtime telemetry (ISSUE 6): step-timeline tracing, metrics
registry + exporters, MFU gauge, anomaly watchdog.

Acceptance bar:

- a pipelined TrainLoop run with MXNET_TELEMETRY=1 and
  MXNET_TRANSFER_GUARD=raise completes with ZERO unblessed host syncs
  while producing a full registry export (window-occupancy, sync-count,
  compile-cache, checkpoint-latency series) — the guard IS the
  regression test for "always-on-cheap";
- the Chrome trace merges per-op events (phase-tagged dispatch/sync)
  and per-step phase spans (window/retire stamped from the
  DispatchWindow's retire timestamps) in one stream;
- the MFU gauge is nonzero and derived from XLA cost_analysis();
- an injected NaN loss and an artificial stall each raise exactly ONE
  structured anomaly event attributed to the correct step number;
- exporters: Prometheus text-format golden output, JSON snapshot schema
  stability, heartbeat interval/shutdown.
"""
import json
import os
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, nd, profiler, telemetry
from mxnet_tpu.analysis import guard as tguard
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, TrainLoop, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher
from mxnet_tpu.telemetry import names
from mxnet_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Zero the process-global telemetry state around every test (metric
    objects cached by instrumentation points survive; values reset)."""
    telemetry.stop_heartbeat()
    telemetry.reset()
    yield
    telemetry.enable(None)
    telemetry.stop_heartbeat()
    telemetry.reset()


def _build(seed=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(3, in_units=8))
    net.initialize()
    return net


def _batch(bs=8, seed=0):
    rng = onp.random.RandomState(seed)
    x = nd.array(rng.randn(bs, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(bs,)).astype("int32"))
    return x, y


def _loop(net=None, inflight=2, **kwargs):
    net = net or _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    return TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=inflight, **kwargs)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("t_events_total", label_key="kind")
    c.inc(label="a")
    c.inc(2.5, label="a")
    c.inc(label="b")
    assert c.value("a") == 3.5 and c.value("b") == 1.0
    with pytest.raises(MXNetError, match="cannot decrease"):
        c.inc(-1, label="a")
    g = reg.gauge("t_level_now")
    assert g.value() is None
    g.set(2.0)
    g.add(0.5)
    assert g.value() == 2.5
    h = reg.histogram("t_wait_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5):
        h.observe(v)
    assert h.count() == 4 and abs(h.sum() - 0.605) < 1e-9
    # p50 falls in the (0.01, 0.1] bucket
    assert 0.01 <= h.percentile(50) <= 0.1
    # get-or-create returns the SAME object; kind drift raises
    assert reg.counter("t_events_total") is c
    with pytest.raises(MXNetError, match="already registered"):
        reg.gauge("t_events_total")


def test_labeled_cardinality_is_bounded():
    reg = MetricsRegistry()
    c = reg.counter("t_many_total", label_key="k")
    for i in range(names.MAX_LABEL_VALUES + 10):
        c.inc(label=f"v{i:03d}")
    vals = c.values()
    assert len(vals) == names.MAX_LABEL_VALUES + 1   # + overflow slot
    assert vals[names.OVERFLOW_LABEL] == 10.0


def test_unlabeled_metric_rejects_labels_and_vice_versa():
    reg = MetricsRegistry()
    c = reg.counter("t_plain_total")
    with pytest.raises(MXNetError, match="without a label"):
        c.inc(label="x")
    lc = reg.counter("t_tagged_total", label_key="kind")
    with pytest.raises(MXNetError, match="requires a"):
        lc.inc()


def test_reset_zeroes_in_place_and_keeps_objects():
    reg = MetricsRegistry()
    c = reg.counter("t_keep_total")
    c.inc(5)
    reg.reset()
    assert c.value() == 0.0
    assert reg.counter("t_keep_total") is c


# ---------------------------------------------------------------------------
# exporters: Prometheus golden, snapshot schema, heartbeat
# ---------------------------------------------------------------------------

def test_prometheus_text_golden():
    reg = MetricsRegistry()
    c = reg.counter("golden_events_total", help="events", label_key="kind")
    c.inc(2, label="a")
    c.inc(label="b")
    g = reg.gauge("golden_level_now", help="level")
    g.set(1.5)
    h = reg.histogram("golden_wait_seconds", help="wait",
                      buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    expected = "\n".join([
        '# HELP golden_events_total events',
        '# TYPE golden_events_total counter',
        'golden_events_total{kind="a"} 2',
        'golden_events_total{kind="b"} 1',
        '# HELP golden_level_now level',
        '# TYPE golden_level_now gauge',
        'golden_level_now 1.5',
        '# HELP golden_wait_seconds wait',
        '# TYPE golden_wait_seconds histogram',
        'golden_wait_seconds_bucket{le="0.1"} 1',
        'golden_wait_seconds_bucket{le="1.0"} 2',
        'golden_wait_seconds_bucket{le="+Inf"} 3',
        'golden_wait_seconds_sum 5.55',
        'golden_wait_seconds_count 3',
    ]) + "\n"
    assert telemetry.prometheus_text(reg) == expected


def test_write_prometheus_env_default_and_atomicity(tmp_path,
                                                    monkeypatch):
    path = str(tmp_path / "metrics" / "mx.prom")
    monkeypatch.setenv("MXNET_PROMETHEUS_FILE", path)
    out = telemetry.write_prometheus()
    assert out == path and os.path.exists(path)
    assert not os.path.exists(path + ".tmp")   # atomic rename, no debris
    text = open(path).read()
    # the default registry always exports the full catalog
    for name in names.CATALOG:
        assert f"# TYPE {name} " in text
    monkeypatch.delenv("MXNET_PROMETHEUS_FILE")
    with pytest.raises(MXNetError, match="MXNET_PROMETHEUS_FILE"):
        telemetry.write_prometheus()


def test_snapshot_schema_stability():
    snap = telemetry.snapshot()
    assert set(snap) == {"schema_version", "time_unix", "counters",
                         "gauges", "histograms", "anomalies"}
    assert snap["schema_version"] == telemetry.SCHEMA_VERSION == 1
    assert set(snap["anomalies"]) == {"count", "recent"}
    # every catalog series is present even at zero — including the
    # acceptance-named ones
    for name in (names.WINDOW_OCCUPANCY, names.WINDOW_CAPACITY):
        assert name in snap["gauges"]
    for name in (names.HOST_SYNCS, names.COMPILE_CACHE_HITS,
                 names.COMPILE_CACHE_MISSES, names.TRAIN_STEPS):
        assert name in snap["counters"]
    for name in (names.CHECKPOINT_CAPTURE_SECONDS,
                 names.CHECKPOINT_SAVE_SECONDS,
                 names.STEP_PHASE_SECONDS, names.STEP_TIME_SECONDS):
        assert name in snap["histograms"]
    json.dumps(snap)   # must be JSON-serializable as-is


def test_heartbeat_interval_and_shutdown(caplog):
    import logging
    before = telemetry.value(names.HEARTBEATS)
    with caplog.at_level(logging.INFO, logger="mxnet_tpu.telemetry"):
        hb = telemetry.start_heartbeat(interval=0.05, write_file=False)
        assert telemetry.start_heartbeat(interval=0.05) is hb  # singleton
        deadline = time.time() + 3.0
        while hb.beats < 2 and time.time() < deadline:
            time.sleep(0.02)
    assert hb.beats >= 2, "heartbeat did not fire on its interval"
    telemetry.stop_heartbeat()
    assert not hb.running
    beats = hb.beats
    time.sleep(0.12)
    assert hb.beats == beats, "heartbeat kept firing after stop"
    telemetry.stop_heartbeat()          # idempotent
    assert telemetry.value(names.HEARTBEATS) - before == beats
    lines = [r.message for r in caplog.records
             if r.message.startswith("mx-telemetry ")]
    assert lines, "heartbeat emitted no structured log line"
    payload = json.loads(lines[0].split(" ", 1)[1])
    assert names.TRAIN_STEPS in payload and "anomalies" in payload


def test_heartbeat_requires_positive_interval(monkeypatch):
    monkeypatch.delenv("MXNET_TELEMETRY_HEARTBEAT_SEC", raising=False)
    with pytest.raises(MXNetError, match="positive interval"):
        telemetry.Heartbeat()


def test_atexit_flush_writes_final_snapshot(tmp_path, monkeypatch):
    """A run that exits BEFORE the first heartbeat interval still leaves
    a final Prometheus snapshot + one structured log line: the atexit
    hook beats once and stops the thread (exporters._atexit_flush —
    installed via atexit.register; exercised directly here since a real
    interpreter exit can't run inside the test)."""
    import atexit
    from mxnet_tpu.telemetry import exporters
    path = str(tmp_path / "final.prom")
    monkeypatch.setenv("MXNET_PROMETHEUS_FILE", path)
    # the hook is registered with the interpreter
    assert exporters._atexit_installed
    hb = telemetry.start_heartbeat(interval=3600.0)   # never fires alone
    assert hb.beats == 0 and not os.path.exists(path)
    exporters._atexit_flush()
    assert os.path.exists(path), "no final Prometheus snapshot written"
    assert hb.beats == 1
    assert not hb.running, "atexit flush must also stop the thread"
    text = open(path).read()
    assert f"# TYPE {names.HEARTBEATS} counter" in text
    # idempotent: a second flush (stopped heartbeat) refreshes the file
    os.remove(path)
    exporters._atexit_flush()
    assert os.path.exists(path)
    assert hb.beats == 1, "stopped heartbeat must not beat again"
    atexit.unregister(exporters._atexit_flush)   # keep the test process
    exporters._atexit_installed = False          # clean for re-install
    exporters._install_atexit()
    assert exporters._atexit_installed


def test_atexit_flush_without_heartbeat_refreshes_file(tmp_path,
                                                       monkeypatch):
    from mxnet_tpu.telemetry import exporters
    path = str(tmp_path / "nohb.prom")
    monkeypatch.setenv("MXNET_PROMETHEUS_FILE", path)
    telemetry.stop_heartbeat()
    exporters._atexit_flush()
    assert os.path.exists(path)
    monkeypatch.delenv("MXNET_PROMETHEUS_FILE")
    exporters._atexit_flush()    # unconfigured: clean no-op
    monkeypatch.setenv("MXNET_TELEMETRY_HEARTBEAT_SEC", "0.25")
    hb = telemetry.Heartbeat()
    assert hb.interval == 0.25 and not hb.running


# ---------------------------------------------------------------------------
# enabling / gating
# ---------------------------------------------------------------------------

def test_enabled_env_parsing(monkeypatch):
    for v, want in (("", False), ("0", False), ("off", False),
                    ("no", False), ("1", True), ("true", True),
                    ("on", True)):
        monkeypatch.setenv("MXNET_TELEMETRY", v)
        assert telemetry.enabled() is want, (v, want)
    monkeypatch.delenv("MXNET_TELEMETRY")
    assert telemetry.enabled() is False
    telemetry.enable(True)
    assert telemetry.enabled() is True
    telemetry.enable(None)
    assert telemetry.enabled() is False


def test_counters_always_on_spans_gated(monkeypatch):
    """Registry counters tick with telemetry OFF; timeline spans do
    not (they need MXNET_TELEMETRY or a running profiler)."""
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    w = engine.DispatchWindow(max_inflight=0, sync_fn=lambda p: None)
    w.push("p", tag=1)
    assert telemetry.value(names.WINDOW_RETIRES) == 1
    assert telemetry.timeline().events() == []
    telemetry.enable(True)
    w.push("p", tag=2)
    phases = {e["phase"] for e in telemetry.timeline().events()}
    assert phases == {"window", "retire"}


# ---------------------------------------------------------------------------
# watchdog: stall + NaN semantics (unit level, exact attribution)
# ---------------------------------------------------------------------------

def test_stall_anomaly_fires_exactly_once_with_step():
    wd = telemetry.watchdog()
    for i in range(8):
        wd.observe_retire(i, dt=0.01)
    assert wd.anomalies() == []
    wd.observe_retire(42, dt=0.2)        # 20x the EWMA
    events = wd.anomalies("stall")
    assert len(events) == 1
    assert events[0]["step"] == 42
    assert telemetry.value(names.ANOMALIES, "stall") == 1
    # recovery re-arms; a second distinct stall fires again
    for i in range(3):
        wd.observe_retire(50 + i, dt=0.01)
    wd.observe_retire(60, dt=0.3)
    assert len(wd.anomalies("stall")) == 2
    # the stalled samples were NOT folded into the EWMA
    assert telemetry.value(names.STEP_TIME_EWMA) < 0.02


def test_stall_factor_env(monkeypatch):
    monkeypatch.setenv("MXNET_WATCHDOG_STALL_FACTOR", "30")
    wd = telemetry.watchdog()
    for i in range(8):
        wd.observe_retire(i, dt=0.01)
    wd.observe_retire(9, dt=0.2)         # 20x < 30x: not a stall
    assert wd.anomalies("stall") == []
    monkeypatch.setenv("MXNET_WATCHDOG_STALL_FACTOR", "bogus")
    assert telemetry.stall_factor() == 4.0


def test_nan_anomaly_fires_once_per_episode():
    wd = telemetry.watchdog()
    finite = onp.ones(4, "float32")
    poisoned = onp.array([1.0, onp.nan], "float32")
    wd.observe_retire(1, payload=finite)
    wd.observe_retire(2, payload=poisoned)
    wd.observe_retire(3, payload=poisoned)   # same episode: no re-fire
    events = wd.anomalies("nan_loss")
    assert [e["step"] for e in events] == [2]
    wd.observe_retire(4, payload=finite)     # recovery
    wd.observe_retire(5, payload=poisoned)   # new episode
    assert [e["step"] for e in wd.anomalies("nan_loss")] == [2, 5]
    # int payloads are never fetched/flagged
    wd.observe_retire(6, payload=onp.array([1, 2], "int32"))
    assert len(wd.anomalies()) == 2


def test_mfu_gauges_from_flops_and_step_time():
    wd = telemetry.watchdog()
    wd.set_model_flops(1e6)
    wd.set_peak_flops(1e9)
    wd.observe_retire(1, dt=0.01)
    wd.observe_retire(2, dt=0.01)
    assert telemetry.value(names.MODEL_FLOPS_PER_STEP) == 1e6
    assert abs(telemetry.value(names.MODEL_FLOPS_PER_SEC) - 1e8) < 1e6
    assert abs(telemetry.value(names.MFU) - 0.1) < 1e-3


# ---------------------------------------------------------------------------
# timeline
# ---------------------------------------------------------------------------

def test_timeline_rejects_unknown_phase():
    with pytest.raises(MXNetError, match="span vocabulary"):
        telemetry.timeline().record("warpdrive", 0.0, 1.0)


def test_timeline_summary_percentiles():
    tl = telemetry.timeline()
    for i in range(100):
        tl.record("dispatch", 0.0, 0.001 * (i + 1), step=i)
    s = tl.summary()["dispatch"]
    assert s["count"] == 100
    assert abs(s["p50_ms"] - 50.5) < 1.0
    assert s["p99_ms"] > 95.0
    # last_steps filters by distinct step number
    s10 = tl.summary(last_steps=10)["dispatch"]
    assert s10["count"] == 10 and s10["p50_ms"] > 90.0


# ---------------------------------------------------------------------------
# the acceptance run: pipelined + guarded + checkpointed + exported
# ---------------------------------------------------------------------------

def test_pipelined_telemetry_zero_unblessed_syncs(tmp_path, monkeypatch):
    """MXNET_TELEMETRY=1 + MXNET_TRANSFER_GUARD=raise + a 12-step
    prefetched pipelined run with periodic checkpoints: zero unblessed
    host syncs, and the export carries the window-occupancy, sync-count,
    compile-cache, and checkpoint-latency series."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    # this test is about sync discipline + exported series, not stall
    # detection (test_artificial_stall_one_anomaly_in_window pins that)
    # — an OS/GC hiccup during the ~2ms steps must not bill a stall
    # anomaly against the zero-anomalies assertion on a loaded CI box
    monkeypatch.setenv("MXNET_WATCHDOG_STALL_FACTOR", "50")
    loop = _loop(checkpoint_dir=str(tmp_path / "ckpt"),
                 checkpoint_every=6)
    x, y = _batch()
    loop.step(x, y)                  # compile outside the counted region
    loop.synchronize()
    telemetry.reset()
    tguard.reset_sync_counts()
    for bx, by in loop.prefetch((x, y) for _ in range(12)):
        loop.step(bx, by)            # raises on any unblessed sync
    loop.synchronize()
    loop.wait()                      # drain the background ckpt write
    assert loop.compiled_step.mode == "fused"
    counts = tguard.sync_counts()
    assert counts.get("wait_to_read", 0) == 0
    assert counts.get("window_retire", 0) == 12

    snap = telemetry.snapshot()
    assert snap["counters"][names.TRAIN_STEPS] == 12
    assert snap["counters"][names.WINDOW_RETIRES] == 12
    assert snap["counters"][names.HOST_SYNCS] == {"window_retire": 12.0}
    assert snap["counters"][names.PREFETCH_BATCHES] == 12
    assert snap["gauges"][names.WINDOW_OCCUPANCY] == 0   # drained
    assert snap["gauges"][names.WINDOW_CAPACITY] == 2
    assert names.COMPILE_CACHE_HITS in snap["counters"]
    assert snap["gauges"][names.COMPILE_CACHE_ENABLED] == 0.0  # unarmed
    # checkpoint-latency series observed real saves (steps 6 and 12)
    assert snap["counters"][names.CHECKPOINT_SAVES] == 2
    assert snap["histograms"][names.CHECKPOINT_CAPTURE_SECONDS][
        "count"] == 2
    assert snap["histograms"][names.CHECKPOINT_SAVE_SECONDS]["count"] == 2
    assert snap["histograms"][names.CHECKPOINT_SAVE_SECONDS]["sum"] > 0
    # every hot-loop phase has 12 observations
    phases = snap["histograms"][names.STEP_PHASE_SECONDS]
    for phase in ("dispatch", "window", "retire"):
        assert phases[phase]["count"] == 12, phase
    assert phases["checkpoint"]["count"] == 2
    assert snap["anomalies"]["count"] == 0
    # the same run exports cleanly as Prometheus text
    text = telemetry.prometheus_text()
    assert 'mx_guard_host_syncs_total{kind="window_retire"} 12' in text
    assert "mx_engine_window_occupancy 0" in text


def test_mfu_gauge_nonzero_from_cost_analysis(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    loop = _loop()
    x, y = _batch()
    flops = loop.arm_mfu(x, y, peak_flops=1e12)
    assert flops and flops > 0, "cost_analysis returned no flops"
    assert telemetry.value(names.MODEL_FLOPS_PER_STEP) == flops
    for _ in range(8):
        loop.step(x, y)
    loop.synchronize()
    mfu = telemetry.value(names.MFU)
    fps = telemetry.value(names.MODEL_FLOPS_PER_SEC)
    assert fps and fps > 0
    assert mfu and 0 < mfu < 1
    assert abs(mfu - fps / 1e12) < 1e-12


def test_step_flops_eager_mode_is_none(monkeypatch):
    """No compiled program -> no MFU numerator (and no crash)."""
    net = _build()
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1})

    def hostile(a, b):
        out = net(a)
        _ = out.asnumpy().sum()          # untraceable: eager fallback
        return loss_blk(out, b)

    step = trainer.compile_step(hostile)
    x, y = _batch()
    step(x, y)
    assert step.mode == "eager"
    assert step.step_flops(x, y) is None


def test_injected_nan_loss_one_anomaly_at_correct_step(monkeypatch):
    """A NaN batch at one known global step raises exactly ONE nan_loss
    anomaly attributed to that step, even though every later loss is
    poisoned too (episode semantics) and retires lag by the window."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    loop = _loop()
    x, y = _batch()
    xnan = nd.array(onp.full((8, 4), onp.nan, "float32"))
    loop.step(x, y)
    loop.synchronize()
    telemetry.reset()
    inject_at = loop.global_step + 7
    for i in range(12):
        loop.step(xnan if loop.global_step + 1 == inject_at else x, y)
    loop.synchronize()
    events = telemetry.watchdog().anomalies()
    assert len(events) == 1
    assert events[0]["kind"] == "nan_loss"
    assert events[0]["step"] == inject_at
    assert telemetry.value(names.ANOMALIES, "nan_loss") == 1
    snap = telemetry.snapshot()
    assert snap["anomalies"]["count"] == 1
    assert snap["anomalies"]["recent"][0]["step"] == inject_at


def test_artificial_stall_one_anomaly_in_window(monkeypatch):
    """An artificially slow retire in a live DispatchWindow raises
    exactly one stall anomaly named with the slow step's tag."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_WATCHDOG_STALL_FACTOR", "8")
    slow_tag = 30

    def sync(payload):
        time.sleep(0.25 if payload == "slow" else 0.002)

    w = engine.DispatchWindow(max_inflight=0, sync_fn=sync)
    for i in range(10):
        w.push("fast", tag=i)
    assert telemetry.watchdog().anomalies() == []
    w.push("slow", tag=slow_tag)
    w.push("fast", tag=slow_tag + 1)
    w.push("fast", tag=slow_tag + 2)
    events = telemetry.watchdog().anomalies("stall")
    assert len(events) == 1
    assert events[0]["step"] == slow_tag
    assert "ms" in events[0]["message"]


# ---------------------------------------------------------------------------
# merged Chrome trace (profiler satellite)
# ---------------------------------------------------------------------------

def test_chrome_trace_merges_op_events_and_step_spans(tmp_path,
                                                      monkeypatch):
    """One dump holds BOTH per-op events (phase-tagged: dispatch-time
    durations are labeled as such, not passed off as run time) and the
    step-phase spans stamped from the DispatchWindow retire
    timestamps."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    loop = _loop()
    x, y = _batch()
    loop.step(x, y)
    loop.synchronize()
    trace = str(tmp_path / "trace.json")
    profiler.set_config(filename=trace)
    profiler.set_state("run")
    try:
        _ = nd.abs(x * -1)               # imperative op -> operator event
        for _ in range(4):
            loop.step(x, y)
        loop.synchronize()
    finally:
        profiler.set_state("stop")
    profiler.dump()
    events = json.load(open(trace))["traceEvents"]
    ops = [e for e in events if e.get("cat") == "operator"]
    steps = [e for e in events if e.get("cat") == "step"]
    assert ops, "no per-op events in the merged trace"
    assert all(e["args"]["phase"] == "dispatch" for e in ops), \
        "async op durations must be labeled as dispatch time"
    got_phases = {e["args"]["phase"] for e in steps}
    assert {"dispatch", "window", "retire"} <= got_phases
    retires = [e for e in steps if e["args"]["phase"] == "retire"]
    assert len(retires) == 4
    assert all(isinstance(e["args"]["step"], int) for e in retires)
    # retire spans end at the retire timestamp: after their window span
    # start (same step), proving the trace is stamped from the window
    for r in retires:
        win = [e for e in steps if e["args"]["phase"] == "window"
               and e["args"]["step"] == r["args"]["step"]]
        assert win and r["ts"] >= win[0]["ts"]


def test_profiler_alone_gets_step_spans(monkeypatch, tmp_path):
    """A running profiler is enough for step spans (no MXNET_TELEMETRY):
    a profile of a pipelined run shows step boundaries by default."""
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    loop = _loop()
    x, y = _batch()
    loop.step(x, y)
    loop.synchronize()
    trace = str(tmp_path / "trace.json")
    profiler.set_config(filename=trace)
    profiler.set_state("run")
    try:
        for _ in range(3):
            loop.step(x, y)
        loop.synchronize()
    finally:
        profiler.set_state("stop")
    profiler.dump()
    events = json.load(open(trace))["traceEvents"]
    assert any(e.get("cat") == "step" for e in events)
    # but the watchdog stayed off: profiling must not add loss fetches
    assert telemetry.watchdog().anomalies() == []


def test_naive_engine_ops_are_sync_phase(monkeypatch):
    monkeypatch.setattr(engine.Engine._instance, "kind", "NaiveEngine",
                        raising=False)
    try:
        assert profiler.Profiler._op_phase() == "sync"
    finally:
        monkeypatch.undo()
    assert profiler.Profiler._op_phase() == "dispatch"


# ---------------------------------------------------------------------------
# prefetcher + engine registry series
# ---------------------------------------------------------------------------

def test_prefetcher_feeds_registry():
    x, y = _batch()
    pf = DevicePrefetcher([(x, y)] * 5, depth=2)
    out = list(pf)
    assert len(out) == 5
    assert telemetry.value(names.PREFETCH_BATCHES) == 5
    wait = telemetry.registry().get(names.PREFETCH_INPUT_WAIT).value()
    assert wait >= 0 and wait == pytest.approx(
        pf.stats["input_wait_ms"] / 1e3, rel=0.05)


def test_window_occupancy_gauge_tracks_pending():
    w = engine.DispatchWindow(max_inflight=3, sync_fn=lambda p: None)
    for i in range(3):
        w.push(i, tag=i)
        assert telemetry.value(names.WINDOW_OCCUPANCY) == i + 1
    w.drain()
    assert telemetry.value(names.WINDOW_OCCUPANCY) == 0
    assert telemetry.value(names.WINDOW_PUSHES) == 3
    assert telemetry.value(names.WINDOW_RETIRES) == 3


def test_window_error_counter():
    def sync(p):
        if p == "bad":
            raise RuntimeError("boom")

    w = engine.DispatchWindow(max_inflight=0, sync_fn=sync)
    w.push("ok", tag=1)
    with pytest.raises(MXNetError):
        w.push("bad", tag=2)
    assert telemetry.value(names.WINDOW_ERRORS) == 1
