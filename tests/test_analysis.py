"""mx.analysis checker tests: golden known-bad programs, each producing
exactly the expected finding — the analyzers are load-bearing for tier-1
(test_fused_step / test_zero_shard assert through them), so THEY need
regression coverage of both directions: known-bad programs must fire the
right rule, known-good programs must stay silent.
"""
import os
import textwrap

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.analysis import guard as tguard
from mxnet_tpu.analysis.hlo import (parse_hlo, parse_replica_groups,
                                    parse_shape_elements)
from mxnet_tpu.analysis.lint import (filter_allowed, lint_function,
                                     lint_source)
from mxnet_tpu.analysis.program import (dtype_drift_scan, expect_mode,
                                        host_transfer_scan)
from mxnet_tpu.analysis.report import ProgramReport
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon import loss as gloss


# ---------------------------------------------------------------------------
# HLO parser
# ---------------------------------------------------------------------------

_CANNED_HLO = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {2}: (1, {}, may-alias) }, entry_computation_layout={(f32[8]{0})->f32[8]{0}}

ENTRY %main (p0: f32[8], p1: f32[8]) -> (f32[8], f32[8]) {
  %p0 = f32[8]{0} parameter(0)
  %p1 = f32[8]{0} parameter(1)
  %all-reduce = f32[8]{0} all-reduce(f32[8]{0} %p0), channel_id=1, replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add
  %dynamic-slice = f32[1]{0} dynamic-slice(f32[8]{0} %all-reduce, s32[] %pid), dynamic_slice_sizes={1}
  %all-reduce.1 = f32[8]{0} all-reduce(f32[8]{0} %p1), channel_id=2, replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add
  %add.9 = f32[8]{0} add(f32[8]{0} %all-reduce.1, f32[8]{0} %p1)
  %reduce-scatter = f32[1]{0} reduce-scatter(f32[8]{0} %p1), channel_id=3, replica_groups=[1,8]<=[8], dimensions={0}, to_apply=%add
  %all-gather = f32[8]{0} all-gather(f32[1]{0} %reduce-scatter), channel_id=4, replica_groups=[1,8]<=[8], dimensions={0}
}
"""


def test_hlo_parser_aliases_and_ops():
    mod = parse_hlo(_CANNED_HLO, num_devices=8)
    assert mod.input_output_alias == [(0, 0), (2, 1)]
    assert mod.ops["all-reduce"].opcode == "all-reduce"
    assert mod.ops["all-reduce"].elements == 8
    assert mod.consumers("all-reduce")[0].opcode == "dynamic-slice"


def test_hlo_replica_group_forms():
    iota = parse_replica_groups("replica_groups=[2,4]<=[8]", 8)
    assert iota == [(0, 1, 2, 3), (4, 5, 6, 7)]
    expl = parse_replica_groups("replica_groups={{0,1},{2,3}}", 4)
    assert expl == [(0, 1), (2, 3)]
    t = parse_replica_groups("replica_groups=[4,2]<=[2,4]T(1,0)", 8)
    assert t == [(0, 4), (1, 5), (2, 6), (3, 7)]


def test_hlo_shape_elements():
    assert parse_shape_elements("f32[4,4]{1,0}") == (16, "f32", 64)
    n, dt, b = parse_shape_elements("(f32[2]{0}, bf16[8]{0})")
    assert (n, dt, b) == (10, "f32", 2 * 4 + 8 * 2)


def test_census_classifies_decomposed_reduce_scatter():
    """The CPU backend's all-reduce + 1/N dynamic-slice pattern counts
    as a (decomposed) reduce_scatter; a consumed-in-full all-reduce
    stays an all_reduce."""
    census = analysis.collective_census(_CANNED_HLO, num_devices=8)
    kinds = census.by_kind
    assert kinds["reduce_scatter"] == 2    # 1 literal + 1 decomposed
    assert kinds["all_reduce"] == 1        # consumed in full -> genuine
    assert kinds["all_gather"] == 1
    dec = [op for op in census.ops if op.decomposed]
    assert len(dec) == 1 and dec[0].name == "all-reduce"


# ---------------------------------------------------------------------------
# golden known-bad programs
# ---------------------------------------------------------------------------

def test_known_bad_leaked_host_callback():
    """A pure_callback smuggled into the step: the jaxpr scan must
    report exactly one host-transfer finding."""
    def leaky(x):
        y = jax.pure_callback(
            lambda a: onp.asarray(a) * 2.0,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y.sum()

    jaxpr = jax.make_jaxpr(leaky)(jnp.ones((4,)))
    findings = host_transfer_scan(jaxpr)
    assert len(findings) == 1
    assert findings[0].rule == "host-transfer"
    assert "callback" in findings[0].message
    # known-good twin: no callback, no finding
    clean = jax.make_jaxpr(lambda x: (x * 2).sum())(jnp.ones((4,)))
    assert host_transfer_scan(clean) == []


def test_known_bad_broken_donation():
    """Donation broken by a dtype-changing output: jax silently DROPS
    the unusable donation at lowering — the audit catches it because
    the caller's expectation (2 donated) exceeds what XLA aliased."""
    def f(x, y):
        return x.astype(jnp.float16), x + y   # x's donation unusable

    import warnings
    lowered = jax.jit(f, donate_argnums=(0, 1)).lower(
        jnp.ones((8, 8)), jnp.ones((8, 8)))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")      # jax's donation warning
        report = analysis.analyze_lowered(lowered, expected_donated=2)
    assert not report.donation.ok
    assert report.donation.aliased == 1
    rules = [f.rule for f in report.findings]
    assert "donation-copy" in rules
    # known-good twin: shape/dtype-preserving update aliases both
    g = jax.jit(lambda x, y: (x + 1, y * 2), donate_argnums=(0, 1))
    rep2 = analysis.analyze_lowered(
        g.lower(jnp.ones((8, 8)), jnp.ones((8, 8))), expected_donated=2)
    assert rep2.donation.ok and rep2.donation.aliased == 2


def test_known_bad_accidental_f64_upcast():
    """f32 -> f64 widening is an error-severity drift, never blessed."""
    from jax.experimental import enable_x64
    with enable_x64():
        jaxpr = jax.make_jaxpr(
            lambda x: x.astype(jnp.float64).sum())(
                jnp.ones((4,), jnp.float32))
    findings = dtype_drift_scan(jaxpr)
    assert any(f.rule == "dtype-drift" and f.severity == "error"
               and "float64" in f.message for f in findings)


def test_known_bad_bf16_widening_and_blessing():
    """bf16 -> f32 widening: flagged by default, blessed under the
    multi-precision master list."""
    jaxpr = jax.make_jaxpr(
        lambda x: x.astype(jnp.float32) * 2.0)(
            jnp.ones((4,), jnp.bfloat16))
    flagged = dtype_drift_scan(jaxpr)
    assert len(flagged) == 1 and not flagged[0].blessed
    blessed = dtype_drift_scan(
        jaxpr, blessed=[("bfloat16", "float32")])
    assert len(blessed) == 1 and blessed[0].blessed


def test_known_bad_allreduce_where_reduce_scatter_expected():
    """A zero-sharded-claiming program whose gradients actually
    all-reduce (replicated update): expect_mode must flag the missing
    reduce-scatter/all-gather AND the unit-sized all-reduce."""
    hlo = textwrap.dedent("""\
    HloModule jit_bad, is_scheduled=true, entry_computation_layout={(f32[1024]{0})->f32[1024]{0}}

    ENTRY %main (p0: f32[1024]) -> f32[1024] {
      %p0 = f32[1024]{0} parameter(0)
      %all-reduce = f32[1024]{0} all-reduce(f32[1024]{0} %p0), channel_id=1, replica_groups=[1,8]<=[8], to_apply=%add
      %add.1 = f32[1024]{0} add(f32[1024]{0} %all-reduce, f32[1024]{0} %p0)
    }
    """)
    report = ProgramReport(mode="zero")
    report.collectives = analysis.collective_census(hlo, num_devices=8)
    report.meta["unit_sizes"] = [1024]
    expect_mode(report, mode="zero", axis=None)
    rules = sorted({f.rule for f in report.findings})
    assert rules == ["collective-mismatch", "per-param-allreduce"]
    assert not report.ok


# ---------------------------------------------------------------------------
# analyze_step + compile_step wiring
# ---------------------------------------------------------------------------

def _tiny_setup(bs=8):
    onp.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(onp.random.randn(bs, 8).astype("float32"))
    y = mx.nd.array(onp.random.randint(0, 4, size=(bs,)).astype("int32"))
    net(x)
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore=None)
    return net, trainer, loss_blk, x, y


def test_analyze_step_plain_fused_clean():
    net, trainer, loss_blk, x, y = _tiny_setup()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    step(x, y)
    report = step.analyze(x, y)
    assert report.mode == "fused"
    assert report.ok, report.summary()
    assert report.collectives.ops == []
    d = report.donation
    assert d.expected == 8 and d.aliased == 8 and not d.copied
    assert d.donated_bytes > 0
    assert report.n_traces == 1          # analysis lower is not a retrace
    assert step.n_traces == 1
    # cached per bucket: second call returns the same object
    assert step.analyze(x, y) is report


def test_analyze_step_eager_reports_not_compiled():
    net, trainer, loss_blk, x, y = _tiny_setup()

    def hostile(a, b):
        out = net(a)
        _ = out.asnumpy().sum()
        return loss_blk(out, b)

    step = trainer.compile_step(hostile)
    step(x, y)
    assert step.mode == "eager"
    report = step.analyze(x, y)
    assert any(f.rule == "not-compiled" for f in report.findings)
    assert report.ok          # warn severity: no hard failure


def test_compile_step_analyze_report_mode():
    net, trainer, loss_blk, x, y = _tiny_setup()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b),
                                analyze="report")
    step(x, y)
    assert step.analysis_report is not None
    assert step.analysis_report.ok


def test_compile_step_analyze_env_default(monkeypatch):
    monkeypatch.setenv("MXNET_ANALYSIS", "report")
    net, trainer, loss_blk, x, y = _tiny_setup()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    step(x, y)
    assert step.analysis_report is not None


def test_compile_step_analyze_raise_on_host_callback():
    """analyze='raise': a loss_fn smuggling a host callback into the
    (otherwise traceable) program raises after the first step.
    jax.debug.print is the canonical culprit — it traces fine (unlike
    pure_callback under JVP, which would demote to eager and be caught
    by the transfer guard instead) but plants a per-step host callback
    in the compiled program."""
    net, trainer, loss_blk, x, y = _tiny_setup()

    def leaky(a, b):
        out = net(a)
        jax.debug.print("activations {}", out._data.sum())
        return loss_blk(out, b)

    step = trainer.compile_step(leaky, analyze="raise")
    with pytest.raises(MXNetError, match="host"):
        step(x, y)


def test_explain_retrace_shapes():
    net, trainer, loss_blk, x, y = _tiny_setup(bs=8)
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    step(x, y)
    assert "only one program" in step.explain_retrace()
    x2 = mx.nd.array(onp.random.randn(4, 8).astype("float32"))
    y2 = mx.nd.array(onp.random.randint(0, 4, size=(4,))
                     .astype("int32"))
    step(x2, y2)
    assert step.n_traces == 2
    why = step.explain_retrace()
    assert "shapes" in why and "(8, 8)" in why and "(4, 8)" in why


# ---------------------------------------------------------------------------
# transfer guard
# ---------------------------------------------------------------------------

def test_transfer_guard_raise_inside_scope():
    a = mx.nd.array(onp.ones((3,), "float32"))
    with pytest.raises(MXNetError, match="device->host sync"):
        with tguard.transfer_guard("raise"):
            a.asnumpy()
    a.asnumpy()                          # outside the scope: fine


def test_transfer_guard_log_records_events():
    tguard.clear_events()
    a = mx.nd.array(onp.ones((3,), "float32"))
    with tguard.transfer_guard("log"):
        a.asnumpy()
        float(a.sum())                   # item() -> asnumpy() funnel
    kinds = [k for k, _ in tguard.events()]
    assert kinds.count("asnumpy") == 2   # one per sync, no double count
    tguard.clear_events()


def test_transfer_guard_allow_transfers():
    a = mx.nd.array(onp.ones((3,), "float32"))
    with tguard.transfer_guard("raise"):
        with tguard.allow_transfers("blessed"):
            a.asnumpy()                  # no raise


def test_transfer_guard_env_catches_planted_asnumpy(monkeypatch):
    """The acceptance path: MXNET_TRANSFER_GUARD=raise + a planted
    .asnumpy() in a compiled region -> MXNetError naming the sync, from
    inside the step call."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    net, trainer, loss_blk, x, y = _tiny_setup()

    def hostile(a, b):
        out = net(a)
        _ = out.asnumpy().sum()          # the plant
        return loss_blk(out, b)

    step = trainer.compile_step(hostile)
    with pytest.raises(MXNetError, match="asnumpy"):
        step(x, y)


def test_transfer_guard_env_log_keeps_training(monkeypatch):
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "log")
    tguard.clear_events()
    net, trainer, loss_blk, x, y = _tiny_setup()

    def hostile(a, b):
        out = net(a)
        _ = out.asnumpy().sum()
        return loss_blk(out, b)

    step = trainer.compile_step(hostile)
    step(x, y)                           # falls back to eager, trains
    assert step.mode == "eager"
    assert any(k == "asnumpy" for k, _ in tguard.events())
    tguard.clear_events()


def test_transfer_guard_clean_step_quiet(monkeypatch):
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    net, trainer, loss_blk, x, y = _tiny_setup()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    for _ in range(2):
        step(x, y)                       # no spurious flags
    assert step.mode == "fused"


# ---------------------------------------------------------------------------
# source lint
# ---------------------------------------------------------------------------

def _lint(body: str):
    src = ("class B:\n"
           "    def forward(self, x, mask=None):\n"
           + textwrap.indent(textwrap.dedent(body), "        "))
    return lint_source(src, filename="snippet.py")


def test_lint_catches_each_rule():
    assert [f.rule for f in _lint("v = x.asnumpy()\nreturn x\n")] \
        == ["MXA001"]
    assert [f.rule for f in _lint("s = float(x.sum())\nreturn x\n")] \
        == ["MXA002"]
    assert [f.rule for f in _lint(
        "if x.sum() > 0:\n    x = x * 2\nreturn x\n")] == ["MXA003"]
    assert [f.rule for f in _lint(
        "import numpy as np\nn = np.random.uniform()\nreturn x\n")] \
        == ["MXA004"]


def test_lint_static_conditions_not_flagged():
    assert _lint("if x.shape[0] > 2:\n    x = x + 1\nreturn x\n") == []
    assert _lint("if mask is not None:\n    x = x + mask\nreturn x\n") \
        == []
    assert _lint("if len(x) > 1:\n    x = x + 1\nreturn x\n") == []


def test_lint_taint_propagates_through_assignment():
    fs = _lint("y = x * 2\nz = y + 1\nif z.min() < 0:\n"
               "    z = -z\nreturn z\n")
    assert [f.rule for f in fs] == ["MXA003"]


def test_lint_inline_allow_blesses():
    fs = _lint("v = x.asnumpy()  # mx-lint: allow=MXA001\nreturn x\n")
    assert len(fs) == 1 and fs[0].blessed
    assert filter_allowed(fs, []) == []


def test_lint_function_on_live_loss_fn():
    def bad_loss(out, label):
        s = out.asnumpy().sum()
        return out.sum() + s

    fs = lint_function(bad_loss)
    assert [f.rule for f in fs] == ["MXA001"]
    assert os.path.basename(__file__).replace(".pyc", ".py") \
        in fs[0].where


def test_lint_cli_roundtrip(tmp_path):
    from mxnet_tpu.analysis.lint import main as lint_main
    p = tmp_path / "m.py"
    p.write_text("class B:\n    def forward(self, x):\n"
                 "        return x.asnumpy()\n")
    assert lint_main([str(p)]) == 1
    ok = tmp_path / "ok.py"
    ok.write_text("class B:\n    def forward(self, x):\n"
                  "        return x * 2\n")
    assert lint_main([str(ok)]) == 0


def test_report_to_dict_and_summary():
    net, trainer, loss_blk, x, y = _tiny_setup()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    step(x, y)
    report = step.analyze(x, y)
    d = report.to_dict()
    assert d["mode"] == "fused" and d["n_traces"] == 1
    assert d["donated_bytes"] > 0 and d["findings"] == []
    s = report.summary()
    assert "donation" in s and "collectives" in s
