"""Tier-1 metric-name sweep: telemetry/names.py is the single source of
truth for every series the framework exports.

Exporter cardinality drifts silently when ad-hoc metric names appear at
call sites — a per-shape or per-step label value, a counter named
outside the convention, a series registered in one branch of one module
that no dashboard knows about. This sweep pins the contract:

- every catalog entry obeys the naming convention (regex + kind-suffix
  rules);
- framework code NEVER registers a metric by string literal — call
  sites import the constant from ``telemetry/names.py``;
- every catalog constant is referenced by live framework code (a dead
  catalog entry would export a forever-zero series and hide the moment
  its instrumentation point silently vanished);
- the registry enforces the convention at runtime (invalid names,
  undeclared ``mx_*`` names, and kind mismatches raise).
"""
import os
import re

import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.telemetry import names
from mxnet_tpu.telemetry.registry import MetricsRegistry

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mxnet_tpu")
NAMES_PY = os.path.join(PKG, "telemetry", "names.py")


def _py_files():
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _read(path):
    with open(path, encoding="utf-8") as f:
        return f.read()


# ---------------------------------------------------------------------------
# the catalog itself
# ---------------------------------------------------------------------------

def test_catalog_names_match_convention():
    assert names.CATALOG, "catalog must not be empty"
    for name, decl in names.CATALOG.items():
        assert name.startswith("mx_"), \
            f"catalog entry {name!r} must use the reserved mx_ prefix"
        assert names.is_valid(name), \
            f"catalog entry {name!r} violates {names.NAME_RE.pattern!r}"
        assert names.kind_ok(name, decl["kind"]), \
            (f"catalog entry {name!r} ({decl['kind']}) violates the "
             "kind-suffix rule (counters *_total, histograms *_seconds)")
        assert decl["help"], f"catalog entry {name!r} needs help text"


def test_catalog_constants_unique():
    consts = {k: v for k, v in vars(names).items()
              if k.isupper() and isinstance(v, str)
              and v.startswith("mx_")}
    assert len(set(consts.values())) == len(consts), \
        "two catalog constants share a metric name"
    for const, value in consts.items():
        assert value in names.CATALOG, \
            f"names.{const} = {value!r} has no CATALOG declaration"


# ---------------------------------------------------------------------------
# call-site discipline across mxnet_tpu/
# ---------------------------------------------------------------------------

_LITERAL_REG = re.compile(
    r"\.\s*(counter|gauge|histogram)\s*\(\s*[\"']")


def test_no_string_literal_metric_registration():
    """Framework code must register through names.py constants — a
    literal at a call site bypasses the single source of truth."""
    offenders = []
    for path in _py_files():
        src = _read(path)
        for m in _LITERAL_REG.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            offenders.append(f"{os.path.relpath(path, REPO)}:{line}")
    assert not offenders, (
        "metric registered by string literal (declare the name in "
        "mxnet_tpu/telemetry/names.py and import the constant — "
        "docs/OBSERVABILITY.md):\n" + "\n".join(offenders))


def test_every_catalog_constant_is_wired():
    """Each constant must be referenced by an instrumentation point or
    exporter OUTSIDE names.py — dead entries export forever-zero series
    and hide a silently-removed instrumentation point."""
    consts = {k for k, v in vars(names).items()
              if k.isupper() and isinstance(v, str)
              and v in names.CATALOG}
    sources = [(_read(p), p) for p in _py_files()
               if os.path.abspath(p) != NAMES_PY]
    dead = []
    for const in sorted(consts):
        pat = re.compile(rf"\b{const}\b")
        if not any(pat.search(src) for src, _ in sources):
            dead.append(const)
    assert not dead, (
        "catalog constants referenced by NO framework code (remove the "
        "entry or restore its instrumentation point): "
        + ", ".join(dead))


# ---------------------------------------------------------------------------
# runtime enforcement (the registry is the gate)
# ---------------------------------------------------------------------------

def test_registry_rejects_convention_violations():
    reg = MetricsRegistry()
    with pytest.raises(MXNetError, match="naming convention"):
        reg.counter("BadName_total")
    with pytest.raises(MXNetError, match="naming convention"):
        reg.counter("single")                 # needs >= 2 tokens
    with pytest.raises(MXNetError, match="kind-suffix"):
        reg.counter("my_events")              # counter without _total
    with pytest.raises(MXNetError, match="kind-suffix"):
        reg.histogram("my_latency_total")     # histogram without unit
    with pytest.raises(MXNetError, match="kind-suffix"):
        reg.gauge("my_level_total")           # gauge with _total


def test_registry_rejects_undeclared_mx_names():
    reg = MetricsRegistry()
    with pytest.raises(MXNetError, match="single source of truth"):
        reg.counter("mx_rogue_series_total")
    # user prefixes stay open for extension
    reg.counter("myapp_events_total")


def test_registry_rejects_kind_and_label_drift():
    reg = MetricsRegistry()
    reg.counter(names.TRAIN_STEPS)
    with pytest.raises(MXNetError, match="already registered"):
        reg.gauge(names.TRAIN_STEPS)
    with pytest.raises(MXNetError, match="declared"):
        # catalog says HOST_SYNCS is labeled by 'kind'
        reg.counter(names.HOST_SYNCS, label_key="step")
    with pytest.raises(MXNetError, match="declared as histogram"):
        # gauge *_seconds passes the suffix rule but not the catalog kind
        reg.gauge(names.STEP_TIME_SECONDS)


def test_default_registry_holds_only_cataloged_framework_names():
    """After importing the framework and touching the instrumented
    layers, every mx_* series in the default registry is cataloged."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.engine import DispatchWindow
    from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher
    DispatchWindow(max_inflight=1, sync_fn=lambda p: None)
    list(DevicePrefetcher([(1,)], depth=0))
    mx.analysis.guard.count_sync("wait_to_read")
    for m in telemetry.registry().metrics():
        assert m.name.startswith("mx_"), \
            f"non-framework series {m.name!r} in the default registry"
        assert m.name in names.CATALOG, \
            f"registered series {m.name!r} missing from the catalog"
        assert names.CATALOG[m.name]["kind"] == m.kind
