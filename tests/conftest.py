"""Test fixtures: virtual 8-device CPU mesh + seed discipline.

Mirrors the reference's test infrastructure (reference:
tests/python/unittest/common.py:164 @with_seed, conftest.py:133
function_scope_seed): every test runs with a known seed, printed on failure
for reproduction. Multi-device tests use XLA's host-platform device
simulation — the TPU-world analog of the reference's
`tools/launch.py --launcher local` multi-process rigs (SURVEY §4).
"""
import os

# Tests always run on the virtual 8-device CPU mesh (set MXNET_TEST_ON_TPU=1
# to exercise real hardware). jax may already be imported by the runtime's
# sitecustomize, so flip the platform through jax.config (still before any
# backend initialization) rather than env vars alone.
if not os.environ.get("MXNET_TEST_ON_TPU"):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as onp
import pytest


@pytest.fixture
def program_report():
    """Factory running the mx.analysis program lint over a
    CompiledTrainStep for one example batch — what the tier-1
    structural assertions in test_fused_step.py / test_zero_shard.py
    use to pin collective/donation expectations per mode."""
    from mxnet_tpu.analysis import program as aprog

    def make(step, *args, **kwargs):
        return aprog.analyze_step(step, *args, **kwargs)

    return make


@pytest.fixture(scope="session")
def lint_allowlist():
    """The checked-in blessed-violation list for the source-lint sweep
    (tests/fixtures/lint_allowlist.txt)."""
    from mxnet_tpu.analysis.lint import load_allowlist
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "lint_allowlist.txt")
    return load_allowlist(path)


@pytest.fixture(autouse=True)
def function_scope_seed(request):
    """Seed every test; print the seed on failure so it can be reproduced
    with MXNET_TEST_SEED (reference common.py:195)."""
    env_seed = os.environ.get("MXNET_TEST_SEED")
    seed = int(env_seed) if env_seed else onp.random.randint(0, 2**31)
    import mxnet_tpu as mx
    mx.random.seed(seed)
    yield
    if request.node.rep_call.failed if hasattr(request.node, "rep_call") else False:
        print(f"\nTest failed with seed {seed}; rerun with MXNET_TEST_SEED={seed}")


@pytest.hookimpl(tryfirst=True, hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    setattr(item, "rep_" + rep.when, rep)
