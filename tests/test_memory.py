"""Device-memory observability (ISSUE 7): HBM accounting, live-buffer
census, memory budget, OOM forensics.

Acceptance bar:

- a ZeRO dp-mesh run's ``live_bytes_by_pool`` shows the ~N× optimizer-
  state reduction vs plain fused, sourced from the CENSUS (weakref pool
  walk over the actual buffers), not a hand computation;
- ``optimizer_state_bytes()`` / ``state_bytes_per_replica`` and the
  census agree byte-for-byte (one accounting path);
- early-break/error in ``DevicePrefetcher`` leaves ZERO retained
  staging buffers, and a 10-step pipelined run leaks zero live arrays
  (``jax.live_arrays()`` delta);
- an injected allocation failure produces exactly ONE anomaly event
  plus one ranked OOM dump file whose schema a golden test validates;
- ``MXNET_MEMORY_BUDGET`` over-budget emits exactly one
  ``memory_budget`` anomaly per episode; recovery re-arms;
- ``profiler.memory_summary()`` routes through the telemetry catalog
  with the documented CPU live-array fallback instead of silent Nones.
"""
import gc
import json
import os

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import engine, nd, profiler, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, TrainLoop, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher
from mxnet_tpu.telemetry import memory as tmem
from mxnet_tpu.telemetry import names

DP = 4


@pytest.fixture(autouse=True)
def _fresh_memory_telemetry():
    """Fresh census + zeroed registry/watchdog around every test."""
    telemetry.reset()
    tmem.census().clear()
    yield
    telemetry.enable(None)
    telemetry.reset()
    tmem.census().clear()


def _build(seed=3, in_units=4, hidden=16, classes=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, in_units=in_units, activation="relu"))
    net.add(nn.Dense(classes, in_units=hidden))
    net.initialize()
    return net


def _batch(bs=8, seed=0, in_units=4, classes=3):
    rng = onp.random.RandomState(seed)
    x = nd.array(rng.randn(bs, in_units).astype("float32"))
    y = nd.array(rng.randint(0, classes, size=(bs,)).astype("int32"))
    return x, y


def _compiled(net=None, opt="adam", kwargs=None):
    net = net or _build()
    trainer = Trainer(net.collect_params(), opt,
                      dict(kwargs or {"learning_rate": 1e-3}))
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    return net, trainer.compile_step(lambda a, b: loss_blk(net(a), b))


class _FakeXlaRuntimeError(Exception):
    pass


_FakeXlaRuntimeError.__name__ = "XlaRuntimeError"


def _oom_exc():
    return _FakeXlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 bytes")


# ---------------------------------------------------------------------------
# byte accounting helper (the one rule)
# ---------------------------------------------------------------------------

def test_device_bytes_numpy_jax_ndarray():
    assert tmem.device_bytes(onp.zeros((4, 5), "float32")) == 80
    assert tmem.device_bytes(jnp.zeros((3, 3), jnp.float32)) == 36
    a = nd.array(onp.zeros((2, 8), "float32"))
    assert tmem.device_bytes(a) == 64
    assert a.device_nbytes == 64
    assert a.nbytes == 64
    assert tmem.device_bytes(jnp.zeros((4,), jnp.bfloat16)) == 8


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
def test_device_bytes_is_per_replica_for_sharded():
    from jax.sharding import NamedSharding, PartitionSpec
    from mxnet_tpu.parallel import make_mesh
    with make_mesh({"dp": DP}, jax.devices()[:DP]) as mesh:
        flat = jax.device_put(
            jnp.zeros((DP * 8,), jnp.float32),
            NamedSharding(mesh.mesh, PartitionSpec("dp")))
        assert tmem.device_bytes(flat) == DP * 8 * 4 // DP
        repl = jax.device_put(jnp.zeros((16,), jnp.float32),
                              mesh.sharding())
        assert tmem.device_bytes(repl) == 64   # replicated: full copy


# ---------------------------------------------------------------------------
# compiled-program memory report
# ---------------------------------------------------------------------------

def test_memory_report_components_and_peak():
    net, step = _compiled()
    x, y = _batch()
    step(x, y)
    r = step.memory_report(x, y)
    assert r is not None
    d = r.to_dict()
    assert set(d) == {"argument_bytes", "output_bytes", "temp_bytes",
                      "generated_code_bytes", "donated_bytes",
                      "peak_bytes"}
    assert all(v >= 0 for v in d.values())
    assert d["argument_bytes"] > 0
    assert d["donated_bytes"] > 0, "param+state donation must alias"
    assert d["peak_bytes"] == (d["argument_bytes"] + d["output_bytes"]
                               + d["temp_bytes"]
                               + d["generated_code_bytes"]
                               - d["donated_bytes"])
    # cached per bucket: the same object comes back
    assert step.memory_report(x, y) is r
    # no-arg merge over analyzed buckets
    merged = step.memory_report()
    assert merged.peak_bytes == r.peak_bytes


def test_memory_report_publishes_hbm_gauges_and_forensics_registry():
    net, step = _compiled()
    x, y = _batch()
    step(x, y)
    step.memory_report(x, y)
    snap = telemetry.snapshot()
    comp = snap["gauges"][names.HBM_COMPILED_BYTES]
    assert comp["argument"] > 0 and "temp" in comp and "donated" in comp
    assert snap["gauges"][names.HBM_PEAK_BYTES] == \
        step.memory_report().peak_bytes
    # registered for OOM dumps
    assert any(v["peak_bytes"] == step.memory_report().peak_bytes
               for v in tmem.compiled_reports().values())


def test_memory_report_merges_buckets_field_wise_max():
    net, step = _compiled()
    x8, y8 = _batch(bs=8)
    x16, y16 = _batch(bs=16)
    step(x8, y8)
    step(x16, y16)
    r8 = step.memory_report(x8, y8)
    r16 = step.memory_report(x16, y16)
    merged = step.memory_report()
    for f in merged.FIELDS:
        assert getattr(merged, f) == max(getattr(r8, f),
                                         getattr(r16, f))


def test_memory_report_none_on_eager():
    net, step = _compiled()
    x, y = _batch()
    step._mode = "eager"
    assert step.memory_report(x, y) is None
    assert step.memory_report() is None


def test_analysis_report_carries_memory():
    net, step = _compiled()
    x, y = _batch()
    step(x, y)
    rep = step.analyze(x, y)
    m = rep.to_dict()["memory"]
    assert m is not None and m["peak_bytes"] > 0
    assert "memory" in rep.summary()


# ---------------------------------------------------------------------------
# live-buffer census
# ---------------------------------------------------------------------------

def test_census_register_and_weakref_release():
    c = tmem.census()
    a = nd.array(onp.zeros((64,), "float32")).track_memory()
    assert c.live_bytes_by_pool()["ndarray"] == 256
    assert c.live_count_by_pool()["ndarray"] == 1
    del a
    gc.collect()
    assert c.live_bytes_by_pool()["ndarray"] == 0


def test_census_rejects_unknown_pool_and_dedupes_across_pools():
    c = tmem.census()
    with pytest.raises(MXNetError, match="unknown census pool"):
        c.register("hbm", nd.array([1.0]))
    a = nd.array(onp.zeros((8,), "float32"))
    c.register("params", a)
    c.register("ndarray", a)   # same underlying buffer, lower pool
    by_pool = c.live_bytes_by_pool()
    assert by_pool["params"] == 32
    assert by_pool["ndarray"] == 0   # POOLS precedence: counted once


def test_census_buffers_ranked_and_reconcile_flags_untracked():
    c = tmem.census()
    small = nd.array(onp.zeros((4,), "float32")).track_memory()
    big = nd.array(onp.zeros((1024,), "float32")).track_memory()
    bufs = c.buffers()
    assert bufs[0]["bytes"] == 4096 and bufs[0]["pool"] == "ndarray"
    assert [b["bytes"] for b in bufs] == \
        sorted((b["bytes"] for b in bufs), reverse=True)
    # an untracked device array shows up in the reconciliation
    stray = jnp.zeros((2048,), jnp.float32) + 0   # materialized, unique
    rec = c.reconcile()
    assert rec["by_pool"]["ndarray"] == 4096 + 16
    assert rec["untracked"]["count"] >= 1
    assert rec["untracked"]["bytes"] >= 8192
    assert rec["untracked"]["top"][0]["bytes"] >= 8192
    del small, big, stray


def test_census_pool_gauges_published_on_export():
    keep = nd.array(onp.zeros((16,), "float32")).track_memory()
    snap = telemetry.snapshot()
    pools = snap["gauges"][names.MEM_POOL_BYTES]
    assert set(pools) == set(tmem.POOLS)
    assert pools["ndarray"] == 64
    assert names.MEM_UNTRACKED_BYTES in snap["gauges"]
    del keep


# ---------------------------------------------------------------------------
# one accounting path: optimizer_state_bytes == census optimizer pool
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_optimizer_state_bytes_agrees_with_census_fused(opt):
    kwargs = {"learning_rate": 1e-2}
    if opt == "sgd":
        kwargs["momentum"] = 0.9
    net, step = _compiled(opt=opt, kwargs=kwargs)
    x, y = _batch()
    step(x, y)
    assert step.mode == "fused"
    reported = step.optimizer_state_bytes()
    assert reported > 0
    assert tmem.census().live_bytes_by_pool()["optimizer"] == reported


def test_optimizer_state_bytes_agrees_with_census_eager():
    net, step = _compiled(opt="adam")
    x, y = _batch()
    step._mode = "eager"
    step(x, y)
    reported = step.optimizer_state_bytes()
    assert reported > 0
    assert tmem.census().live_bytes_by_pool()["optimizer"] == reported


def test_params_pool_registered_after_first_step():
    net, step = _compiled()
    x, y = _batch()
    step(x, y)
    n_param_bytes = sum(
        int(onp.prod(p.shape)) * 4
        for p in net.collect_params().values())
    assert tmem.census().live_bytes_by_pool()["params"] == n_param_bytes


# ---------------------------------------------------------------------------
# the ZeRO acceptance bar: census-measured ~N× optimizer-state drop
# ---------------------------------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
def test_zero_census_state_drop_vs_plain(monkeypatch):
    """The arXiv:2004.13336 headline, measured: the census `optimizer`
    pool drops ~DP× between plain fused and ZeRO-sharded, and both
    modes' `optimizer_state_bytes()` agree with the census
    byte-for-byte."""
    from mxnet_tpu.parallel import make_mesh, shard_batch
    monkeypatch.setenv("MXNET_ZERO_SHARD_MIN_SIZE", "1")
    x, y = _batch()

    def measure(mode):
        gc.collect()
        tmem.census().clear()
        net, step = _compiled(opt="adam",
                              kwargs={"learning_rate": 1e-2})
        if mode == "zero":
            with make_mesh({"dp": DP}, jax.devices()[:DP]) as mesh:
                step(shard_batch(x, mesh), shard_batch(y, mesh))
            assert step.zero_sharded
        else:
            step(x, y)
            assert not step.zero_sharded
        census_bytes = tmem.census().live_bytes_by_pool()["optimizer"]
        assert census_bytes == step.optimizer_state_bytes()
        # keep the net alive until after the census read
        return census_bytes, net

    full, net_a = measure("plain")
    shard, net_z = measure("zero")
    assert full > 0 and shard > 0
    # padding of non-divisible shapes costs a little; still ~1/DP
    assert shard <= full / DP * 1.5, (full, shard)
    # under zero the state buffers really are NamedSharding-partitioned
    assert any(b["sharded"]
               for b in tmem.census().buffers("optimizer"))


# ---------------------------------------------------------------------------
# prefetch staging release
# ---------------------------------------------------------------------------

def _staged_batches(n, bs=4):
    rng = onp.random.RandomState(0)
    for _ in range(n):
        yield (nd.array(rng.randn(bs, 4).astype("float32")),
               nd.array(rng.randint(0, 3, size=(bs,)).astype("int32")))


def test_prefetcher_stages_into_census_pool():
    pf = DevicePrefetcher(_staged_batches(4), depth=2)
    it = iter(pf)
    b = next(it)
    assert tmem.census().live_bytes_by_pool()["prefetch"] > 0
    for b in it:
        pass
    del b, it, pf
    gc.collect()
    assert tmem.census().live_bytes_by_pool()["prefetch"] == 0


def test_prefetcher_early_break_releases_all_staging():
    """Early break with a deep queue: the consumer's cleanup drains the
    staged batches deterministically — zero retained staging buffers,
    counted by the census."""
    pf = DevicePrefetcher(_staged_batches(32), depth=4)
    for i, b in enumerate(pf):
        if i == 1:
            break
    del b
    gc.collect()
    assert tmem.census().live_bytes_by_pool()["prefetch"] == 0
    assert tmem.census().live_count_by_pool()["prefetch"] == 0


def test_prefetcher_error_releases_all_staging():
    def bad_source():
        yield from _staged_batches(3)
        raise RuntimeError("source died")

    pf = DevicePrefetcher(bad_source(), depth=4)
    it = iter(pf)
    consumed = [next(it) for _ in range(3)]
    with pytest.raises(RuntimeError, match="source died"):
        next(it)
    del consumed, it
    gc.collect()
    assert tmem.census().live_bytes_by_pool()["prefetch"] == 0


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_live_arrays_delta_zero_across_pipelined_run():
    """Tier-1 leak test: a 10-step pipelined TrainLoop run creates NO
    net-new live device arrays — every staged batch, async loss and
    donated intermediate is released by the time the window drains."""
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=2)
    x, y = _batch()

    def run(steps):
        for bx, by in loop.prefetch((x, y) for _ in range(steps)):
            loop.step(bx, by)
        loop.synchronize()

    run(3)           # warmup: compile, materialize optimizer state
    gc.collect()
    before = len(jax.live_arrays())
    run(10)
    gc.collect()
    after = len(jax.live_arrays())
    assert after - before == 0, \
        f"pipelined run leaked {after - before} live arrays"


# ---------------------------------------------------------------------------
# memory budget watchdog
# ---------------------------------------------------------------------------

def test_parse_budget_forms():
    assert tmem.parse_budget("1024") == 1024
    assert tmem.parse_budget("2k") == 2048
    assert tmem.parse_budget("2K") == 2048
    assert tmem.parse_budget("1.5g") == int(1.5 * (1 << 30))
    assert tmem.parse_budget("500MB") == 500 * (1 << 20)
    assert tmem.parse_budget("0.5", capacity=1000) == 500
    assert tmem.parse_budget("0.5") is None     # fraction, no capacity
    assert tmem.parse_budget("") is None
    assert tmem.parse_budget("nonsense") is None
    assert tmem.parse_budget("-4") is None


def test_budget_unset_is_noop(monkeypatch):
    monkeypatch.delenv("MXNET_MEMORY_BUDGET", raising=False)
    assert tmem.maybe_check_budget() is None
    assert telemetry.watchdog().anomalies("memory_budget") == []


def test_budget_over_emits_exactly_one_anomaly_per_episode(monkeypatch):
    a = nd.array(onp.zeros((1024,), "float32")).track_memory()
    monkeypatch.setenv("MXNET_MEMORY_BUDGET", "1")   # 1 byte: over
    for i in range(5):
        st = tmem.maybe_check_budget(step=i + 1)
        assert st["over"]
    evs = telemetry.watchdog().anomalies("memory_budget")
    assert len(evs) == 1, "one event per episode, not per check"
    assert evs[0]["step"] == 1
    assert "MXNET_MEMORY_BUDGET" in evs[0]["message"]
    assert telemetry.value(names.ANOMALIES, "memory_budget") == 1
    # recovery re-arms: under budget, then over again -> second event
    monkeypatch.setenv("MXNET_MEMORY_BUDGET", "1g")
    assert not tmem.maybe_check_budget(step=6)["over"]
    monkeypatch.setenv("MXNET_MEMORY_BUDGET", "1")
    assert tmem.maybe_check_budget(step=7)["over"]
    evs = telemetry.watchdog().anomalies("memory_budget")
    assert len(evs) == 2 and evs[1]["step"] == 7
    del a


def test_budget_checked_at_window_retire(monkeypatch):
    """The engine feeds the budget check from the blessed retire when
    telemetry is enabled — a pipelined over-budget run raises exactly
    one memory_budget anomaly."""
    monkeypatch.setenv("MXNET_MEMORY_BUDGET", "1")
    telemetry.enable(True)
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=1)
    x, y = _batch()
    for _ in range(6):
        loop.step(x, y)
    loop.synchronize()
    assert len(telemetry.watchdog().anomalies("memory_budget")) == 1
    snap = telemetry.snapshot()
    assert snap["gauges"][names.MEM_BUDGET_BYTES] == 1


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def test_is_resource_exhausted_matches_chain():
    assert tmem.is_resource_exhausted(_oom_exc())
    assert not tmem.is_resource_exhausted(ValueError("shape mismatch"))
    try:
        try:
            raise _oom_exc()
        except Exception as inner:
            raise MXNetError("step 3 failed") from inner
    except MXNetError as wrapped:
        assert tmem.is_resource_exhausted(wrapped)


def test_oom_dump_golden(tmp_path, monkeypatch):
    """The acceptance bar: one injected allocation failure -> exactly
    one anomaly event + one ranked dump file with the documented
    schema."""
    monkeypatch.setenv("MXNET_MEMORY_DUMP_DIR", str(tmp_path))
    # populate pools so the dump ranks something real
    big = nd.array(onp.zeros((4096,), "float32")).track_memory()
    small = nd.array(onp.zeros((8,), "float32")).track_memory()
    net, step = _compiled()
    x, y = _batch()
    step(x, y)
    step.memory_report(x, y)

    win = engine.DispatchWindow(
        max_inflight=0,
        sync_fn=lambda p: (_ for _ in ()).throw(_oom_exc()),
        what="train step")
    with pytest.raises(MXNetError, match="step 7"):
        win.push(object(), tag=7)

    evs = telemetry.watchdog().anomalies("oom")
    assert len(evs) == 1, "exactly one oom anomaly per failure"
    assert evs[0]["step"] == 7
    files = [f for f in os.listdir(tmp_path)
             if f.startswith("mx_oom_") and f.endswith(".json")]
    assert len(files) == 1
    assert not any(f.endswith(".tmp") for f in os.listdir(tmp_path))
    with open(tmp_path / files[0]) as f:
        dump = json.load(f)
    # golden schema
    assert set(dump) == {
        "schema_version", "time_unix", "seam", "step", "error",
        "budget_bytes", "device_stats", "live_bytes_by_pool",
        "untracked", "top_buffers", "compiled", "hints"}
    assert dump["schema_version"] == tmem.DUMP_SCHEMA_VERSION == 1
    assert dump["seam"] == "dispatch-window retire"
    assert dump["step"] == 7
    assert "RESOURCE_EXHAUSTED" in dump["error"]
    assert set(dump["live_bytes_by_pool"]) == set(tmem.POOLS)
    assert dump["live_bytes_by_pool"]["ndarray"] >= 4096 * 4
    # ranked: bytes strictly descending order
    sizes = [b["bytes"] for b in dump["top_buffers"]]
    assert sizes == sorted(sizes, reverse=True) and sizes[0] >= 16384
    assert all(set(b) >= {"pool", "shape", "dtype", "bytes"}
               for b in dump["top_buffers"])
    # per-bucket compiled peaks are attached
    assert any(v["peak_bytes"] > 0 for v in dump["compiled"].values())
    assert dump["hints"], "sizing hints must not be empty"
    assert telemetry.value(names.OOM_DUMPS) == 1
    del big, small


def test_oom_single_event_across_nested_seams(tmp_path, monkeypatch):
    """An OOM propagating through several seams (retire -> waitall ->
    user catch) records ONE dump + ONE anomaly — the exception chain is
    marked at the innermost seam."""
    monkeypatch.setenv("MXNET_MEMORY_DUMP_DIR", str(tmp_path))
    exc = _oom_exc()
    path1 = tmem.maybe_record_oom(exc, "inner seam", step=1)
    assert path1 is not None
    wrapped = MXNetError("outer")
    wrapped.__cause__ = exc
    assert tmem.maybe_record_oom(wrapped, "outer seam", step=1) is None
    assert len(telemetry.watchdog().anomalies("oom")) == 1
    assert len(list(os.listdir(tmp_path))) == 1


def test_oom_without_dump_dir_still_fires_anomaly(monkeypatch):
    monkeypatch.delenv("MXNET_MEMORY_DUMP_DIR", raising=False)
    assert tmem.maybe_record_oom(_oom_exc(), "seam") is None
    evs = telemetry.watchdog().anomalies("oom")
    assert len(evs) == 1
    assert "MXNET_MEMORY_DUMP_DIR" in evs[0]["message"]
    assert telemetry.value(names.OOM_DUMPS) == 0


def test_non_oom_errors_do_not_trigger_forensics(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_MEMORY_DUMP_DIR", str(tmp_path))
    win = engine.DispatchWindow(
        max_inflight=0,
        sync_fn=lambda p: (_ for _ in ()).throw(ValueError("nan grads")),
        what="train step")
    with pytest.raises(MXNetError):
        win.push(object(), tag=1)
    assert telemetry.watchdog().anomalies("oom") == []
    assert list(os.listdir(tmp_path)) == []


def test_oom_guard_reraises_unchanged():
    with pytest.raises(_FakeXlaRuntimeError):
        with tmem.oom_guard("test seam", step=2):
            raise _oom_exc()
    assert len(telemetry.watchdog().anomalies("oom")) == 1


def test_sizing_hints_name_the_dominant_knob():
    # replicated optimizer state dominates -> ZeRO hint
    hints = tmem._sizing_hints(
        {"params": 100, "optimizer": 200, "prefetch": 0,
         "checkpoint": 0, "ndarray": 0}, {}, None)
    assert any("ZeRO" in h for h in hints)
    # staged batches -> prefetch/window hint
    hints = tmem._sizing_hints(
        {"params": 0, "optimizer": 0, "prefetch": 50, "checkpoint": 0,
         "ndarray": 0}, {}, None)
    assert any("MXNET_DEVICE_PREFETCH" in h for h in hints)
    # XLA temps dominate the compiled peak -> batch/remat hint
    hints = tmem._sizing_hints(
        {p: 0 for p in tmem.POOLS},
        {"fused:bucket1": {"peak_bytes": 100, "temp_bytes": 90}}, None)
    assert any("remat" in h for h in hints)


# ---------------------------------------------------------------------------
# device stats / profiler routing (satellite)
# ---------------------------------------------------------------------------

def test_memory_summary_cpu_fallback_documented_not_silent():
    keep = nd.array(onp.zeros((256,), "float32"))
    out = profiler.memory_summary()
    assert out, "every local device must report"
    for dev, s in out.items():
        assert set(s) == {"bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit", "source"}
        assert s["source"] in ("allocator", "live_arrays")
        assert s["bytes_in_use"] is not None
    if jax.default_backend() == "cpu":
        assert all(s["source"] == "live_arrays" for s in out.values())
        assert sum(s["bytes_in_use"] for s in out.values()) >= 1024
    # routed through the catalog: the gauges carry the same numbers
    reg = telemetry.registry()
    for dev, s in out.items():
        assert reg.gauge(names.MEM_DEVICE_IN_USE).value(dev) == \
            s["bytes_in_use"]
    del keep


def test_checkpoint_capture_lands_in_census_pool(tmp_path):
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     checkpoint_dir=str(tmp_path), checkpoint_every=None)
    x, y = _batch()
    loop.step(x, y)
    loop.synchronize()
    from mxnet_tpu.checkpoint.manager import TrainCheckpointManager
    state = loop.checkpoint_manager.save(
        1, trainer=trainer, net=net, block=True)
    assert tmem.census().live_bytes_by_pool()["checkpoint"] > 0
    assert any(b["host"] for b in tmem.census().buffers("checkpoint"))
    del state
    gc.collect()
    assert tmem.census().live_bytes_by_pool()["checkpoint"] == 0
