"""API-surface tail, part 2: profiler instrumentation objects,
recordio.pack_img, util/context shims.

Reference analogs: profiler.py:228-520 (Domain/Task/Frame/Event/
Counter/Marker), recordio.py:469 pack_img, util.py tail, context.py
gpu_memory_info.
"""
import json

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler, recordio, util


def test_profiler_instrumentation_objects(tmp_path):
    out = tmp_path / "trace.json"
    profiler.set_config(filename=str(out))
    profiler.set_state("run")
    dom = profiler.Domain("mydomain")
    task = dom.new_task("loadtask")
    with task:
        nd.array(onp.ones(4)).asnumpy()
    frame = dom.new_frame("frame0")
    frame.start()
    frame.stop()
    ev = profiler.Event("standalone")
    with ev:
        pass
    ctr = dom.new_counter("examples", 10)
    ctr.increment(5)
    ctr -= 3
    marker = dom.new_marker("epoch-end")
    marker.mark("process")
    profiler.set_state("stop")
    profiler.dump()
    events = json.load(open(out))["traceEvents"]
    names = {e["name"] for e in events}
    assert {"loadtask", "frame0", "standalone", "examples",
            "epoch-end"} <= names
    cat = {e["name"]: e.get("cat") for e in events}
    assert cat["loadtask"] == "mydomain"
    assert cat["frame0"] == "mydomain:frame"
    counter_vals = [e["args"]["value"] for e in events
                    if e["name"] == "examples"]
    assert counter_vals == [10, 15, 12]
    inst = [e for e in events if e["name"] == "epoch-end"]
    assert inst and inst[0]["ph"] == "i" and inst[0]["s"] == "p"
    with pytest.raises(mx.MXNetError):
        dom.new_task("bad").stop()  # stop before start


def test_profiler_deprecated_aliases(tmp_path):
    with pytest.warns(DeprecationWarning):
        profiler.profiler_set_config(
            filename=str(tmp_path / "p.json"))
    with pytest.warns(DeprecationWarning):
        profiler.profiler_set_state("stop")
    assert profiler.set_kvstore_handle(None) is None


def test_pack_img_roundtrip(tmp_path):
    # smooth gradient: JPEG-friendly (random noise is destroyed by DCT)
    gy, gx = onp.mgrid[0:16, 0:16]
    img = onp.stack([gy * 16, gx * 16, (gy + gx) * 8],
                    axis=-1).astype("uint8")
    header = recordio.IRHeader(0, 3.0, 7, 0)
    for fmt, tol in ((".png", 0), (".jpg", 40)):
        s = recordio.pack_img(header, img, quality=(9 if fmt == ".png"
                                                    else 95),
                              img_fmt=fmt)
        h2, img2 = recordio.unpack_img(s)
        assert h2.label == 3.0 and h2.id == 7
        assert img2.shape == img.shape
        assert onp.abs(img2.astype(int) - img.astype(int)).max() <= tol
    with pytest.raises(mx.MXNetError):
        recordio.pack_img(header, img, img_fmt=".webp")
    # full file round trip through the indexed writer
    w = recordio.MXIndexedRecordIO(str(tmp_path / "d.idx"),
                                   str(tmp_path / "d.rec"), "w")
    w.write_idx(0, recordio.pack_img(header, img, img_fmt=".png"))
    w.close()
    r = recordio.MXIndexedRecordIO(str(tmp_path / "d.idx"),
                                   str(tmp_path / "d.rec"), "r")
    h3, img3 = recordio.unpack_img(r.read_idx(0))
    onp.testing.assert_array_equal(img3, img)


def test_util_tail():
    @util.set_module("mxnet_tpu.numpy")
    def f():
        pass
    assert f.__module__ == "mxnet_tpu.numpy"

    assert util.np_ufunc_legal_option("casting", "safe")
    assert not util.np_ufunc_legal_option("casting", "bogus")
    assert util.np_ufunc_legal_option("dtype", "float32")
    assert not util.np_ufunc_legal_option("nope", 1)

    with util.np_array(True):
        arr = util.default_array([1.0, 2.0])
        import mxnet_tpu.numpy as mnp
        assert isinstance(arr, mnp.ndarray)
    arr2 = util.default_array([1.0, 2.0])
    assert type(arr2).__name__ == "NDArray"

    assert util.is_np_default_dtype() is False
    with util.np_default_dtype(True):
        assert util.is_np_default_dtype() is True
    assert util.is_np_default_dtype() is False

    @util.use_np_default_dtype
    def inside():
        return util.is_np_default_dtype()
    assert inside() is True

    @util.use_np_shape
    def shaped():
        return util.is_np_shape()
    assert shaped() is True

    util.setenv("MXT_TEST_ENV_VAR", "42")
    assert util.getenv("MXT_TEST_ENV_VAR") == "42"
    util.setenv("MXT_TEST_ENV_VAR", None)
    assert util.getenv("MXT_TEST_ENV_VAR") is None

    assert util.get_gpu_count() == 0
    with pytest.raises(mx.MXNetError):
        util.get_gpu_memory(0)
    with pytest.raises(mx.MXNetError):
        mx.context.gpu_memory_info(0)


def test_numpy_fallback_decorator():
    import numpy as real_np

    @util.numpy_fallback
    def my_median(x):
        return real_np.median(x)

    out = my_median(nd.array(onp.array([1.0, 3.0, 2.0])))
    # scalar results pass through as numpy scalars (arrays wrap to mx)
    assert float(out) == 2.0
    a = nd.array(onp.ones(3))
    a.attach_grad()
    from mxnet_tpu import autograd
    with autograd.record():
        with pytest.raises(mx.MXNetError, match="fallback"):
            my_median(a)
