"""Optimizer lr/wd multiplier resolution (ISSUE 2 satellite).

Reference precedence (python/mxnet/optimizer/optimizer.py _get_lr/_get_wd):
an INDEX-keyed entry in set_lr_mult/set_wd_mult wins over a NAME-keyed one
for the same parameter; a name-keyed entry applies only when no index key
exists. Also pins the ZeRO eligibility flags and the per-shard
hyperparameter packing helper the sharded fused step uses.
"""
import numpy as onp

from mxnet_tpu import optimizer as opt_mod


def _sgd_with_names():
    opt = opt_mod.SGD(learning_rate=1.0, wd=1.0,
                      param_idx2name={0: "fc_weight", 1: "fc_bias"})
    return opt


def test_lr_mult_index_beats_name():
    opt = _sgd_with_names()
    opt.set_lr_mult({"fc_weight": 0.5, 0: 0.25})
    # both key kinds present for index 0: the index key wins
    assert opt._get_lr(0) == 0.25
    # only a name key for index 1
    opt.set_lr_mult({"fc_bias": 2.0})
    assert opt._get_lr(1) == 2.0
    # neither -> unity
    assert opt._get_lr(0) == 1.0


def test_wd_mult_index_beats_name():
    opt = _sgd_with_names()
    opt.set_wd_mult({"fc_weight": 0.5, 0: 4.0, "fc_bias": 0.0})
    assert opt._get_wd(0) == 4.0     # index key shadows the name key
    assert opt._get_wd(1) == 0.0     # name key applies


def test_mults_without_idx2name():
    """With no idx2name the index doubles as the name; both spellings
    resolve and index still takes precedence."""
    opt = opt_mod.SGD(learning_rate=1.0, wd=1.0)
    opt.set_lr_mult({0: 0.1})
    assert opt._get_lr(0) == onp.float32(0.1)
    assert opt._get_lr(1) == 1.0


def test_elementwise_update_flags():
    """The ZeRO-1 sharded fused step may engage only for elementwise
    rules; norm-based and row-reducing rules must opt out."""
    assert opt_mod.SGD().elementwise_update
    assert opt_mod.Adam().elementwise_update
    assert opt_mod.AdamW().elementwise_update
    assert opt_mod.RMSProp().elementwise_update
    assert not opt_mod.LARS().elementwise_update
    assert not opt_mod.LAMB().elementwise_update
    assert not opt_mod.LANS().elementwise_update
    assert not opt_mod.GroupAdaGrad().elementwise_update
    assert not opt_mod.SGLD().elementwise_update


def test_pack_shard_hparams_layout():
    """Per-element packing: each member's scalar repeats over its flat
    segment; the pad tail is lr=wd=0, t=1 (finite bias corrections)."""
    lrs = onp.asarray([0.1, 0.2, 0.3], onp.float32)
    wds = onp.asarray([1.0, 2.0, 3.0], onp.float32)
    ts = onp.asarray([5, 6, 7], onp.int32)
    # bucket holds params 2 and 0 (sizes 3 and 2), padded to 8
    lv, wv, tv = opt_mod.Optimizer.pack_shard_hparams(
        lrs, wds, ts, [2, 0], [3, 2], 8)
    onp.testing.assert_allclose(
        lv, [0.3, 0.3, 0.3, 0.1, 0.1, 0.0, 0.0, 0.0], rtol=1e-6)
    onp.testing.assert_allclose(
        wv, [3.0, 3.0, 3.0, 1.0, 1.0, 0.0, 0.0, 0.0])
    onp.testing.assert_array_equal(tv, [7, 7, 7, 5, 5, 1, 1, 1])
