"""Detection data pipeline (reference python/mxnet/image/detection.py):
box-transforming augmenters keep labels consistent with the pixels, and
ImageDetIter batches variable-object labels into fixed shapes."""
import random as pyrandom

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.image.detection import (CreateDetAugmenter,
                                       CreateMultiRandCropAugmenter,
                                       DetBorrowAug, DetHorizontalFlipAug,
                                       DetRandomCropAug, DetRandomPadAug,
                                       DetRandomSelectAug, ImageDetIter)


def _scene(rng, size=64, square=12):
    """Bright square on dark noise; label = its normalized corner box."""
    img = (rng.rand(size, size, 3) * 20).astype("uint8")
    x0 = rng.randint(2, size - square - 2)
    y0 = rng.randint(2, size - square - 2)
    img[y0:y0 + square, x0:x0 + square] = 255
    label = onp.array([[1, x0 / size, y0 / size,
                        (x0 + square) / size, (y0 + square) / size]],
                      "float32")
    return label, img


def _box_pixels(img, box):
    """Mean intensity inside the normalized box of an HWC image."""
    h, w = img.shape[:2]
    x1, y1, x2, y2 = (int(box[1] * w), int(box[2] * h),
                      int(onp.ceil(box[3] * w)), int(onp.ceil(box[4] * h)))
    region = img[y1:y2, x1:x2]
    return float(region.mean()) if region.size else 0.0


def test_flip_moves_boxes_with_pixels():
    rng = onp.random.RandomState(0)
    label, img = _scene(rng)
    aug = DetHorizontalFlipAug(p=1.0)
    src, lab = aug(nd.array(img.astype("float32")), label)
    assert _box_pixels(src.asnumpy(), lab[0]) > 150
    # class id untouched
    assert lab[0, 0] == 1


def test_random_crop_keeps_box_on_object():
    rng = onp.random.RandomState(1)
    pyrandom.seed(1)
    aug = DetRandomCropAug(min_object_covered=0.9, area_range=(0.3, 0.9),
                           max_attempts=100)
    crops = 0
    for _ in range(10):
        label, img = _scene(rng)
        src, lab = aug(nd.array(img.astype("float32")), label)
        a = src.asnumpy()
        if a.shape != img.shape:
            crops += 1
        assert lab.shape[0] >= 1  # min_object_covered=0.9 keeps the object
        assert _box_pixels(a, lab[0]) > 120, (a.shape, lab)
        assert (lab[:, 1:5] >= 0).all() and (lab[:, 1:5] <= 1).all()
    assert crops >= 5  # the augmenter did actually crop most of the time


def test_random_crop_ejects_uncovered_objects():
    # crop confined to the left half can never cover a right-half object
    pyrandom.seed(3)
    img = onp.zeros((64, 64, 3), "uint8")
    img[10:20, 40:50] = 255
    label = onp.array([[0, 40 / 64, 10 / 64, 50 / 64, 20 / 64]], "float32")
    aug = DetRandomCropAug(min_object_covered=0.99, area_range=(0.9, 1.0),
                           max_attempts=5)
    src, lab = aug(nd.array(img.astype("float32")), label)
    # either no acceptable crop (unchanged) or object still covered
    if src.asnumpy().shape == img.shape:
        onp.testing.assert_array_equal(lab, label)
    else:
        assert _box_pixels(src.asnumpy(), lab[0]) > 120


def test_random_pad_shrinks_boxes_onto_canvas():
    rng = onp.random.RandomState(2)
    pyrandom.seed(2)
    aug = DetRandomPadAug(area_range=(1.5, 3.0), pad_val=(7, 7, 7))
    label, img = _scene(rng)
    src, lab = aug(nd.array(img.astype("float32")), label)
    a = src.asnumpy()
    assert a.shape[0] > img.shape[0] or a.shape[1] > img.shape[1]
    assert _box_pixels(a, lab[0]) > 120
    # area under padding: boxes shrink proportionally
    assert (lab[0, 3] - lab[0, 1]) < (label[0, 3] - label[0, 1])


def test_select_aug_skip_prob_and_multicrop_factory():
    aug = CreateMultiRandCropAugmenter(
        min_object_covered=[0.3, 0.9], area_range=[(0.3, 0.9), (0.5, 1.0)],
        skip_prob=0.0)
    assert isinstance(aug, DetRandomSelectAug)
    assert len(aug.aug_list) == 2
    skip = DetRandomSelectAug(aug.aug_list, skip_prob=1.0)
    rng = onp.random.RandomState(4)
    label, img = _scene(rng)
    src, lab = skip(nd.array(img.astype("float32")), label)
    onp.testing.assert_array_equal(lab, label)  # skipped: untouched


def test_create_det_augmenter_full_stack_preserves_object():
    rng = onp.random.RandomState(5)
    pyrandom.seed(5)
    augs = CreateDetAugmenter((3, 48, 48), rand_crop=0.5, rand_pad=0.5,
                              rand_mirror=True, min_object_covered=0.9,
                              area_range=(0.5, 2.0), brightness=0.1,
                              contrast=0.1, saturation=0.1, hue=0.1,
                              pca_noise=0.05, rand_gray=0.1,
                              mean=True, std=True)
    for _ in range(5):
        label, img = _scene(rng)
        src, lab = nd.array(img.astype("float32")), label
        for a in augs:
            src, lab = a(src, lab)
        out = src.asnumpy()
        assert out.shape == (48, 48, 3)  # forced to data_shape
        assert lab.shape[0] >= 1
        assert (lab[:, 1:5] >= 0).all() and (lab[:, 1:5] <= 1).all()


def test_image_det_iter_batches_and_pads_labels():
    rng = onp.random.RandomState(6)
    items = []
    for i in range(7):
        label, img = _scene(rng)
        if i % 2:  # second object on some images: variable object count
            label = onp.concatenate([label, label + [0, .01, .01, .01, .01]])
        items.append((label, img))
    it = ImageDetIter(batch_size=3, data_shape=(3, 32, 32), imglist=items,
                      mean=True, std=True)
    assert it.label_shape == (2, 5)
    b = it.next()
    assert b.data[0].shape == (3, 3, 32, 32)
    assert b.label[0].shape == (3, 2, 5)
    lab = b.label[0].asnumpy()
    # padding rows carry the -1 no-object sentinel
    assert ((lab[:, :, 0] >= 0) | (lab[:, :, 0] == -1)).all()
    n = 1
    for _ in it:
        n += 1
    assert n == 3  # ceil(7/3) with pad
    it.reset()
    it.next()


def test_image_det_iter_parses_flat_header_labels():
    flat = onp.array([2, 5,  # header_width, obj_width
                      1, 0.1, 0.2, 0.5, 0.6,
                      0, 0.3, 0.3, 0.7, 0.9,
                      -1, -1, -1, -1, -1], "float32")
    parsed = ImageDetIter._parse_label(flat)
    assert parsed.shape == (2, 5)
    onp.testing.assert_allclose(parsed[0], [1, 0.1, 0.2, 0.5, 0.6])


def test_image_det_iter_sync_label_shape():
    rng = onp.random.RandomState(7)
    a = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                     imglist=[_scene(rng) for _ in range(2)])
    lab2, img2 = _scene(rng)
    lab2 = onp.concatenate([lab2, lab2, lab2])
    b = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                     imglist=[(lab2, img2)])
    a.sync_label_shape(b)
    assert a.label_shape == b.label_shape == (3, 5)
    assert a.next().label[0].shape == (2, 3, 5)


def test_image_det_iter_rejects_bad_args():
    with pytest.raises(MXNetError):
        ImageDetIter(batch_size=2, data_shape=(3, 32, 32))
    with pytest.raises(MXNetError):
        ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                     imglist=[(onp.zeros((1, 4), "float32"),
                               onp.zeros((8, 8, 3), "uint8"))])


def test_std_only_normalization_stays_finite():
    rng = onp.random.RandomState(8)
    it = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                      imglist=[_scene(rng) for _ in range(2)], std=True)
    data = it.next().data[0].asnumpy()
    assert onp.isfinite(data).all()
    assert data.max() <= 8.0  # divided by ~58, not raw uint8


def test_random_pad_grayscale_image():
    pyrandom.seed(9)
    img = onp.zeros((40, 40, 1), "uint8")
    img[5:15, 5:15] = 200
    label = onp.array([[0, 5 / 40, 5 / 40, 15 / 40, 15 / 40]], "float32")
    aug = DetRandomPadAug(area_range=(1.5, 2.5), pad_val=(9, 9, 9),
                          max_attempts=100)
    src, lab = aug(nd.array(img.astype("float32")), label)
    a = src.asnumpy()
    assert a.shape[2] == 1
    assert a.shape[0] > 40 or a.shape[1] > 40


def test_last_batch_roll_over_and_validation():
    rng = onp.random.RandomState(10)
    it = ImageDetIter(batch_size=3, data_shape=(3, 32, 32),
                      imglist=[_scene(rng) for _ in range(7)],
                      last_batch_handle="roll_over")
    n1 = sum(1 for _ in it)          # 2 full batches, 1 deferred
    assert n1 == 2
    it.reset()                        # leftover leads the new epoch: 8 items
    n2 = sum(1 for _ in it)
    assert n2 == 2  # 8 -> 2 full batches, 2 deferred
    with pytest.raises(MXNetError):
        ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                     imglist=[_scene(rng)], last_batch_handle="dicard")


def test_image_det_iter_from_recordio(tmp_path):
    """path_imgrec source: records carry the flat header-label form and
    raw image payloads; batches match the imglist-sourced pipeline."""
    from mxnet_tpu import recordio as rio
    rng = onp.random.RandomState(11)
    path = str(tmp_path / "det.rec")
    writer = rio.MXRecordIO(path, "w")
    items = []
    for i in range(5):
        label, img = _scene(rng, size=32, square=8)
        items.append((label, img))
        flat = onp.concatenate([[2, 5], label.ravel()]).astype("float32")
        header = rio.IRHeader(flag=len(flat), label=flat, id=i, id2=0)
        # raw uint8 CHW payload (imdecode_or_raw's synthetic-record form)
        writer.write(rio.pack(header,
                              img.transpose(2, 0, 1).tobytes()))
    writer.close()

    it = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                      path_imgrec=path)
    it_list = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                           imglist=items)
    assert it.label_shape == (1, 5)
    seen = 0
    for batch, ref in zip(it, it_list):
        data, lab = batch.data[0].asnumpy(), batch.label[0].asnumpy()
        assert data.shape == (2, 3, 32, 32)
        assert lab.shape == (2, 1, 5)
        # record round-trip parity: identical batches either source
        onp.testing.assert_allclose(data, ref.data[0].asnumpy(),
                                    rtol=1e-6)
        onp.testing.assert_allclose(lab, ref.label[0].asnumpy(),
                                    rtol=1e-6)
        for b in range(2):
            if lab[b, 0, 0] < 0:
                continue
            assert _box_pixels(data[b].transpose(1, 2, 0), lab[b, 0]) > 120
            seen += 1
    assert seen >= 5
