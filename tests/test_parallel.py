"""Multi-device correctness tests for the distributed layer.

The TPU-world analog of the reference's multi-process-on-localhost rigs
(reference tests/nightly/dist_sync_kvstore.py invariants, launched via
tests/nightly/test_distributed_training-gpu.sh:25-39): every test here runs
on the virtual 8-device CPU mesh the conftest provisions.

Covers: collective numerics per mesh axis (parallel/collectives.py), the
8-device data-parallel Trainer == single-device Trainer invariant, gradient
compression round-trips inside a sharded step, and mesh helpers.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import (allgather, allreduce, broadcast_axis,
                                make_mesh, ppermute, reduce_scatter,
                                shard_batch, shard_params)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def _blocks(x: onp.ndarray, n: int):
    """Split along dim0 into n per-shard blocks."""
    return x.reshape((n, x.shape[0] // n) + x.shape[1:])


@pytest.fixture(params=[("dp", 4), ("tp", 2)])
def axis_mesh(request):
    """One mesh, exercised per named axis (reference tests kvstore per comm
    path; here per mesh axis)."""
    name, size = request.param
    with make_mesh({"dp": 4, "tp": 2}) as mesh:
        yield mesh, name, size


def test_allreduce_numerics(axis_mesh):
    mesh, axis, n = axis_mesh
    x = onp.arange(8 * 3, dtype="float32").reshape(8, 3)
    out = allreduce(nd.array(x), axis=axis, mesh=mesh).asnumpy()
    blocks = _blocks(x, n)
    golden = onp.tile(blocks.sum(axis=0), (n, 1))
    onp.testing.assert_allclose(out, golden, rtol=1e-6)
    # mean + max variants
    out_mean = allreduce(nd.array(x), axis=axis, mesh=mesh, op="mean").asnumpy()
    onp.testing.assert_allclose(out_mean, golden / n, rtol=1e-6)
    out_max = allreduce(nd.array(x), axis=axis, mesh=mesh, op="max").asnumpy()
    onp.testing.assert_allclose(out_max, onp.tile(blocks.max(axis=0), (n, 1)))


def test_allgather_numerics(axis_mesh):
    mesh, axis, n = axis_mesh
    x = onp.arange(8 * 2, dtype="float32").reshape(8, 2)
    out = allgather(nd.array(x), axis=axis, mesh=mesh).asnumpy()
    # every shard gathers all blocks tiled along dim0 -> full x again
    onp.testing.assert_allclose(out, x)


def test_reduce_scatter_numerics(axis_mesh):
    mesh, axis, n = axis_mesh
    x = onp.arange(8 * 2, dtype="float32").reshape(8, 2)
    out = reduce_scatter(nd.array(x), axis=axis, mesh=mesh).asnumpy()
    # input replicated per shard; psum_scatter sums the n identical copies
    # and hands each shard its tile -> reassembled = n * x
    onp.testing.assert_allclose(out, n * x, rtol=1e-6)


def test_broadcast_axis_numerics(axis_mesh):
    mesh, axis, n = axis_mesh
    x = onp.arange(8 * 2, dtype="float32").reshape(8, 2)
    for src in (0, n - 1):
        out = broadcast_axis(nd.array(x), axis=axis, mesh=mesh,
                             src=src).asnumpy()
        golden = onp.tile(_blocks(x, n)[src], (n, 1))
        onp.testing.assert_allclose(out, golden)


def test_ppermute_ring(axis_mesh):
    mesh, axis, n = axis_mesh
    x = onp.arange(8 * 2, dtype="float32").reshape(8, 2)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = ppermute(nd.array(x), perm, axis=axis, mesh=mesh).asnumpy()
    golden = onp.concatenate([_blocks(x, n)[(i - 1) % n] for i in range(n)])
    onp.testing.assert_allclose(out, golden)


# ---------------------------------------------------------------------------
# Round-trip parity vs numpy (ISSUE 2 satellite): the collective
# compositions the ZeRO-1 sharded update rides, including the padded
# non-divisible leading dim.
# ---------------------------------------------------------------------------

def test_reduce_scatter_allgather_roundtrip(axis_mesh):
    """reduce_scatter then allgather of the sharded tiles reconstructs
    the numpy golden (n * x for a replicated operand): the reduce-
    scatter → update → all-gather decomposition is lossless."""
    mesh, axis, n = axis_mesh
    x = onp.arange(8 * 3, dtype="float32").reshape(8, 3) + 1.0
    rs = reduce_scatter(nd.array(x), axis=axis, mesh=mesh)
    out = allgather(rs, axis=axis, mesh=mesh).asnumpy()
    onp.testing.assert_allclose(out, n * x, rtol=1e-6)


def test_reduce_scatter_padded_non_divisible(axis_mesh):
    """Leading dims not divisible by the axis size zero-pad through the
    scatter and slice back — numpy parity on the original shape (the
    tentpole's padded flat-shard layout at the NDArray level)."""
    mesh, axis, n = axis_mesh
    for lead in (7, 5, 9):
        if lead % n == 0:
            continue
        x = onp.arange(lead * 2, dtype="float32").reshape(lead, 2) + 1.0
        out = reduce_scatter(nd.array(x), axis=axis, mesh=mesh)
        assert out.shape == (lead, 2)
        onp.testing.assert_allclose(out.asnumpy(), n * x, rtol=1e-6)
    # 1-D flat buffers (the fused-step unit layout)
    flat = onp.arange(11, dtype="float32") + 1.0
    out = reduce_scatter(nd.array(flat), axis=axis, mesh=mesh)
    onp.testing.assert_allclose(out.asnumpy(), n * flat, rtol=1e-6)


def test_ppermute_roundtrip(axis_mesh):
    """A ring rotation followed by its inverse is the identity."""
    mesh, axis, n = axis_mesh
    x = onp.arange(8 * 2, dtype="float32").reshape(8, 2)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    inv = [(i, (i - 1) % n) for i in range(n)]
    back = ppermute(ppermute(nd.array(x), fwd, axis=axis, mesh=mesh),
                    inv, axis=axis, mesh=mesh).asnumpy()
    onp.testing.assert_allclose(back, x)


# ---------------------------------------------------------------------------
# DP Trainer invariant: 8-device sharded batch == single-device batch
# (the reference dist_sync_kvstore.py:60-120 invariant, mesh edition)
# ---------------------------------------------------------------------------

def _build_net(seed):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8), nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    net(nd.zeros((1, 8)))
    return net


def _train(net, xs, ys, sharded_mesh=None, steps=3, kvstore="tpu"):
    if sharded_mesh is not None:
        # replicate weights over the mesh (TPU-native split_and_load: one
        # logical array, replicated; batch sharded over dp)
        shard_params(net.collect_params(), rules=[], mesh=sharded_mesh)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore=kvstore)
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    for s in range(steps):
        x, y = nd.array(xs[s]), nd.array(ys[s])
        if sharded_mesh is not None:
            x = shard_batch(x, sharded_mesh)
            y = shard_batch(y, sharded_mesh)
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
    return {p.name: p.data().asnumpy() for p in
            net.collect_params().values()}


def test_dp_trainer_matches_single_device():
    rng = onp.random.RandomState(0)
    xs = [rng.randn(16, 8).astype("float32") for _ in range(3)]
    ys = [rng.randint(0, 4, size=(16,)).astype("int32") for _ in range(3)]

    ref = _train(_build_net(5), xs, ys, sharded_mesh=None)
    with make_mesh({"dp": 8}) as mesh:
        got = _train(_build_net(5), xs, ys, sharded_mesh=mesh)
    assert ref.keys() == got.keys()
    for k in ref:
        onp.testing.assert_allclose(got[k], ref[k], rtol=2e-4, atol=2e-5,
                                    err_msg=f"param {k} diverged under DP")


def test_dp_trainer_replica_lists_match_single():
    """Reference-style per-device replica DP: grads pushed as an 8-entry
    list must reduce to the same update as the concatenated batch."""
    kv = mx.kvstore.create("tpu")
    n = 8
    grads = [nd.array(onp.full((4,), float(i + 1), dtype="float32"))
             for i in range(n)]
    kv.init("w", nd.zeros((4,)))
    kv.pushpull("w", grads)
    expected = sum(range(1, n + 1))
    for g in grads:
        onp.testing.assert_allclose(g.asnumpy(), onp.full((4,), expected))


def test_dp_gradients_are_sharded_then_correct():
    """Gradient wrt a replicated weight from a dp-sharded batch equals the
    single-device gradient (XLA inserts the psum)."""
    rng = onp.random.RandomState(1)
    x_np = rng.randn(16, 8).astype("float32")
    net = _build_net(7)
    with autograd.record():
        loss = (net(nd.array(x_np)) ** 2).mean()
    loss.backward()
    ref_g = net.collect_params()["0.weight"].grad().asnumpy()

    net2 = _build_net(7)
    with make_mesh({"dp": 8}) as mesh:
        shard_params(net2.collect_params(), rules=[], mesh=mesh)
        xsh = shard_batch(nd.array(x_np), mesh)
        with autograd.record():
            loss = (net2(xsh) ** 2).mean()
        loss.backward()
    got_g = net2.collect_params()["0.weight"].grad().asnumpy()
    onp.testing.assert_allclose(got_g, ref_g, rtol=2e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# Gradient compression (reference dist_sync_kvstore.py compression section)
# ---------------------------------------------------------------------------

def test_compression_error_feedback_converges():
    """2bit quantization with error feedback: the residual carries the
    quantization error so the running sum of compressed grads tracks the
    true sum (the reference's error-feedback contract)."""
    from mxnet_tpu.parallel.compression import GradientCompression
    gc = GradientCompression(type="2bit", threshold=0.5)
    rng = onp.random.RandomState(3)
    true_sum = onp.zeros(32, dtype="float32")
    sent_sum = onp.zeros(32, dtype="float32")
    for _ in range(60):
        g = rng.uniform(-0.2, 0.2, size=32).astype("float32")
        true_sum += g
        sent_sum += gc.compress_decompress(nd.array(g), key=("w", 0)).asnumpy()
    # each step's wire values are from {-t, 0, t}; cumulative drift stays
    # bounded by one threshold per coordinate thanks to error feedback
    assert onp.max(onp.abs(true_sum - sent_sum)) <= 0.5 + 1e-6


def test_compression_residual_keyed_per_key():
    from mxnet_tpu.parallel.compression import GradientCompression
    gc = GradientCompression(type="2bit", threshold=0.5)
    a = nd.array(onp.full(4, 0.3, dtype="float32"))
    gc.compress_decompress(a, key=("a", 0))
    gc.compress_decompress(a, key=("b", 0))
    assert set(gc._residuals) == {("a", 0), ("b", 0)}
    # residual for 'a' is 0.3 (below threshold -> sent 0); second push of
    # 0.3 accumulates to 0.6 -> sends the 0.5 step
    out = gc.compress_decompress(a, key=("a", 0)).asnumpy()
    onp.testing.assert_allclose(out, onp.full(4, 0.5))


def test_compression_applies_through_pushpull():
    """pushpull with compression must hand back the COMPRESSED sum in the
    caller's arrays (regression: result was written to throwaway copies,
    silently disabling compression through Trainer)."""
    kv = mx.kvstore.create("tpu")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    g0 = nd.array(onp.full(4, 2.0, dtype="float32"))
    g1 = nd.array(onp.full(4, 2.0, dtype="float32"))
    kv.pushpull("g", [g0, g1])
    # each replica quantizes 2.0 -> +0.5; reduced sum = 1.0 (NOT 4.0)
    onp.testing.assert_allclose(g0.asnumpy(), onp.full(4, 1.0))
    onp.testing.assert_allclose(g1.asnumpy(), onp.full(4, 1.0))
    # residual error 1.5 feeds back: next push of 0 still emits +0.5
    z0 = nd.array(onp.zeros(4, dtype="float32"))
    z1 = nd.array(onp.zeros(4, dtype="float32"))
    kv.pushpull("g", [z0, z1])
    onp.testing.assert_allclose(z0.asnumpy(), onp.full(4, 1.0))


def test_compression_in_sharded_trainer_step():
    """Compression attached through the kvstore inside a DP sharded step
    runs and trains (numerics are lossy by design; assert movement +
    finiteness)."""
    rng = onp.random.RandomState(0)
    xs = [rng.randn(16, 8).astype("float32") for _ in range(3)]
    ys = [rng.randint(0, 4, size=(16,)).astype("int32") for _ in range(3)]
    net = _build_net(9)
    w0 = net.collect_params()["0.weight"].data().asnumpy().copy()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05},
                      kvstore="tpu",
                      compression_params={"type": "2bit", "threshold": 0.01})
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    with make_mesh({"dp": 8}) as mesh:
        shard_params(net.collect_params(), rules=[], mesh=mesh)
        for s in range(3):
            x = shard_batch(nd.array(xs[s]), mesh)
            y = shard_batch(nd.array(ys[s]), mesh)
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(16)
    w1 = net.collect_params()["0.weight"].data().asnumpy()
    assert onp.all(onp.isfinite(w1)) and not onp.allclose(w0, w1)
    kv = trainer._kvstore
    # residuals keyed by (key, replica) — never by buffer id
    assert all(isinstance(k, tuple) for k in kv._compression._residuals)


# ---------------------------------------------------------------------------
# mesh helpers
# ---------------------------------------------------------------------------

def test_make_mesh_infer_and_errors():
    from mxnet_tpu.parallel.mesh import current_mesh
    with make_mesh({"dp": -1, "tp": 2}) as mesh:
        assert mesh.shape == {"dp": 4, "tp": 2}
        assert current_mesh() is mesh
    assert current_mesh() is None
    with pytest.raises(mx.MXNetError):
        make_mesh({"dp": 3, "tp": 3})


def test_shard_batch_places_shards():
    with make_mesh({"dp": 8}) as mesh:
        x = shard_batch(nd.array(onp.arange(32, dtype="float32")
                                 .reshape(16, 2)), mesh)
        assert len(x._data.sharding.device_set) == 8
        onp.testing.assert_allclose(
            x.asnumpy(), onp.arange(32, dtype="float32").reshape(16, 2))


# ---------------------------------------------------------------------------
# dist.initialize retry/backoff (ISSUE 3 satellite)
# ---------------------------------------------------------------------------

def test_dist_initialize_retries_then_clear_error(monkeypatch):
    """A flaky coordinator RPC is retried with backoff; exhausting the
    budget raises an MXNetError naming the coordinator, not a raw RPC
    error."""
    from mxnet_tpu.parallel import dist
    calls = []
    monkeypatch.setattr(dist, "_initialized", [False])
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: calls.append(kw) or (_ for _ in ()).throw(
            RuntimeError("DEADLINE_EXCEEDED: rpc to master")))
    monkeypatch.setattr(dist.time, "sleep", lambda s: None)
    monkeypatch.setenv("MXNET_DIST_INIT_RETRIES", "4")
    with pytest.raises(mx.MXNetError) as ei:
        dist.initialize(coordinator_address="10.0.0.1:9000",
                        num_processes=2, process_id=1)
    assert len(calls) == 4
    assert "10.0.0.1:9000" in str(ei.value)
    assert "4 attempts" in str(ei.value)
    assert not dist._initialized[0]


def test_dist_initialize_succeeds_after_transient_failure(monkeypatch):
    from mxnet_tpu.parallel import dist
    monkeypatch.setattr(dist, "_initialized", [False])
    attempts = []

    def flaky(**kw):
        attempts.append(kw)
        if len(attempts) < 3:
            raise RuntimeError("UNAVAILABLE: connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(dist.time, "sleep", lambda s: None)
    monkeypatch.setenv("MXNET_DIST_INIT_RETRIES", "5")
    monkeypatch.setenv("MXNET_DIST_INIT_TIMEOUT", "7.5")
    dist.initialize(coordinator_address="h:1", num_processes=1,
                    process_id=0)
    assert dist._initialized[0]
    assert len(attempts) == 3
    assert attempts[0]["initialization_timeout"] == 7.5
