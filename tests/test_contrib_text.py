"""contrib.text: Vocabulary + token embeddings.

Reference analog: tests/python/unittest/test_contrib_text.py — the same
contracts (index 0 = unknown, frequency-then-alphabetical ordering,
first-seen-wins embedding load, header-line skip, strict
update_token_vectors) against local-file fixtures (no egress).
"""
import collections

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import text


def test_count_tokens_from_str():
    source = "life is a peach \n life is good"
    c = text.utils.count_tokens_from_str(source)
    assert c["life"] == 2 and c["is"] == 2 and c["peach"] == 1
    c2 = text.utils.count_tokens_from_str("Life", to_lower=True,
                                          counter_to_update=c)
    assert c2["life"] == 3


def test_vocabulary_indexing_order():
    counter = collections.Counter(
        ["a", "b", "b", "c", "c", "c", "some_word$"])
    v = text.Vocabulary(counter)
    # unknown first, then by descending freq, ties alphabetical
    assert v.idx_to_token == ["<unk>", "c", "b", "a", "some_word$"]
    assert v.to_indices("c") == 1
    assert v.to_indices(["c", "missing"]) == [1, 0]
    assert v.to_tokens([0, 2]) == ["<unk>", "b"]
    assert len(v) == 5


def test_vocabulary_limits_and_reserved():
    counter = collections.Counter(["a", "b", "b", "c", "c", "c"])
    v = text.Vocabulary(counter, most_freq_count=2, min_freq=2,
                        unknown_token="<UNK>",
                        reserved_tokens=["<pad>", "<bos>"])
    assert v.idx_to_token[:3] == ["<UNK>", "<pad>", "<bos>"]
    # most_freq_count=2 caps counter keys; min_freq=2 drops 'a'
    assert "a" not in v.token_to_idx
    assert v.reserved_tokens == ["<pad>", "<bos>"]
    with pytest.raises(ValueError):
        text.Vocabulary(counter, min_freq=0)
    with pytest.raises(ValueError):
        text.Vocabulary(counter, unknown_token="<pad>",
                        reserved_tokens=["<pad>"])
    with pytest.raises(ValueError):
        text.Vocabulary(counter, reserved_tokens=["<pad>", "<pad>"])
    with pytest.raises(ValueError):
        v.to_tokens(99)


def _write_embedding(path, lines):
    with open(path, "w", encoding="utf8") as f:
        f.write("\n".join(lines) + "\n")
    return str(path)


def test_custom_embedding_loading(tmp_path):
    p = _write_embedding(tmp_path / "emb.txt", [
        "a 0.1 0.2 0.3",
        "b 0.5 0.6 0.7",
        "<unk> 9.0 9.0 9.0",
    ])
    e = text.embedding.CustomEmbedding(p)
    assert e.vec_len == 3
    assert e.to_indices("a") == 1 and e.to_indices("b") == 2
    # unknown vector comes from the file's <unk> line
    onp.testing.assert_allclose(e.idx_to_vec[0].asnumpy(),
                                [9.0, 9.0, 9.0], rtol=1e-6)
    vec = e.get_vecs_by_tokens("b")
    assert vec.shape == (3,)
    onp.testing.assert_allclose(vec.asnumpy(), [0.5, 0.6, 0.7], rtol=1e-6)
    vecs = e.get_vecs_by_tokens(["a", "nope"])
    assert vecs.shape == (2, 3)
    onp.testing.assert_allclose(vecs.asnumpy()[1], [9.0, 9.0, 9.0],
                                rtol=1e-6)


def test_custom_embedding_header_dup_and_unknown_init(tmp_path):
    p = _write_embedding(tmp_path / "emb.txt", [
        "2 3",                  # fasttext-style header: skipped w/ warning
        "a 0.1 0.2 0.3",
        "a 0.9 0.9 0.9",        # duplicate: skipped w/ warning
        "b 0.5 0.6 0.7",
    ])
    with pytest.warns(UserWarning):
        e = text.embedding.CustomEmbedding(
            p, init_unknown_vec=nd.ones)
    onp.testing.assert_allclose(e.idx_to_vec[0].asnumpy(), [1.0, 1.0, 1.0],
                                rtol=1e-6)
    onp.testing.assert_allclose(
        e.get_vecs_by_tokens("a").asnumpy(), [0.1, 0.2, 0.3], rtol=1e-6)
    # dimension mismatch raises
    bad = _write_embedding(tmp_path / "bad.txt",
                           ["a 0.1 0.2 0.3", "b 0.5 0.6"])
    with pytest.raises(ValueError, match="[Dd]imension"):
        text.embedding.CustomEmbedding(bad)


def test_lower_case_backup(tmp_path):
    p = _write_embedding(tmp_path / "emb.txt", ["hello 1 2"])
    e = text.embedding.CustomEmbedding(p)
    onp.testing.assert_allclose(
        e.get_vecs_by_tokens("HELLO",
                             lower_case_backup=True).asnumpy(),
        [1.0, 2.0], rtol=1e-6)
    onp.testing.assert_allclose(
        e.get_vecs_by_tokens("HELLO").asnumpy(), [0.0, 0.0], atol=1e-6)


def test_update_token_vectors(tmp_path):
    p = _write_embedding(tmp_path / "emb.txt", ["a 1 1", "b 2 2"])
    e = text.embedding.CustomEmbedding(p)
    e.update_token_vectors("a", nd.array([7.0, 8.0]))
    onp.testing.assert_allclose(e.get_vecs_by_tokens("a").asnumpy(),
                                [7.0, 8.0], rtol=1e-6)
    e.update_token_vectors(["a", "b"],
                           nd.array([[1.5, 2.5], [3.5, 4.5]]))
    onp.testing.assert_allclose(e.idx_to_vec[1:].asnumpy(),
                                [[1.5, 2.5], [3.5, 4.5]], rtol=1e-6)
    with pytest.raises(ValueError, match="unknown"):
        e.update_token_vectors("nope", nd.array([0.0, 0.0]))
    # the unknown vector updates only when named explicitly
    e.update_token_vectors("<unk>", nd.array([5.0, 5.0]))
    onp.testing.assert_allclose(e.idx_to_vec[0].asnumpy(), [5.0, 5.0],
                                rtol=1e-6)
    with pytest.raises(ValueError):
        e.update_token_vectors(["a", "b"], nd.array([1.0, 2.0]))


def test_embedding_with_reserved_tokens_alignment(tmp_path):
    """Pre-seeded reserved tokens must not shift file tokens' vector
    rows (review finding round 4)."""
    p = _write_embedding(tmp_path / "emb.txt",
                         ["a 1 1", "b 2 2", "c 3 3"])
    e = text.embedding.CustomEmbedding(
        p, reserved_tokens=["<pad>", "<bos>"], init_unknown_vec=nd.ones)
    assert e.idx_to_token[:3] == ["<unk>", "<pad>", "<bos>"]
    assert e.idx_to_vec.shape == (6, 2)
    onp.testing.assert_allclose(e.get_vecs_by_tokens("a").asnumpy(),
                                [1.0, 1.0], rtol=1e-6)
    onp.testing.assert_allclose(e.get_vecs_by_tokens("c").asnumpy(),
                                [3.0, 3.0], rtol=1e-6)
    onp.testing.assert_allclose(e.get_vecs_by_tokens("<pad>").asnumpy(),
                                [1.0, 1.0], rtol=1e-6)  # init vector


def test_embedding_with_vocabulary(tmp_path):
    p = _write_embedding(tmp_path / "emb.txt",
                         ["a 1 1", "b 2 2", "c 3 3"])
    counter = collections.Counter(["b", "b", "zzz"])
    v = text.Vocabulary(counter)
    e = text.embedding.CustomEmbedding(p, vocabulary=v)
    # embedding reindexed to the vocabulary, not the file
    assert e.idx_to_token == v.idx_to_token
    assert e.idx_to_vec.shape == (len(v), 2)
    onp.testing.assert_allclose(
        e.get_vecs_by_tokens("b").asnumpy(), [2.0, 2.0], rtol=1e-6)
    # vocab token absent from the file gets the unknown vector
    onp.testing.assert_allclose(
        e.get_vecs_by_tokens("zzz").asnumpy(), [0.0, 0.0], atol=1e-6)


def test_composite_embedding(tmp_path):
    p1 = _write_embedding(tmp_path / "e1.txt", ["a 1 1", "b 2 2"])
    p2 = _write_embedding(tmp_path / "e2.txt", ["b 9 9 9", "c 8 8 8"])
    v = text.Vocabulary(collections.Counter(["a", "b", "c"]))
    ce = text.embedding.CompositeEmbedding(
        v, [text.embedding.CustomEmbedding(p1),
            text.embedding.CustomEmbedding(p2)])
    assert ce.vec_len == 5
    vb = ce.get_vecs_by_tokens("b").asnumpy()
    onp.testing.assert_allclose(vb, [2.0, 2.0, 9.0, 9.0, 9.0], rtol=1e-6)
    va = ce.get_vecs_by_tokens("a").asnumpy()
    onp.testing.assert_allclose(va, [1.0, 1.0, 0.0, 0.0, 0.0], atol=1e-6)
    # a file whose every vector row is skipped fails loudly
    p3 = _write_embedding(tmp_path / "e3.txt", ["b 9", "c 8"])
    with pytest.raises(ValueError, match="No embedding vectors"):
        text.embedding.CustomEmbedding(p3)


def test_glove_fasttext_local_root(tmp_path):
    root = tmp_path / "embroot"
    gdir = root / "glove"
    gdir.mkdir(parents=True)
    _write_embedding(gdir / "glove.6B.50d.txt", ["a 1 2", "b 3 4"])
    g = text.embedding.create("glove",
                              pretrained_file_name="glove.6B.50d.txt",
                              embedding_root=str(root))
    assert g.vec_len == 2
    onp.testing.assert_allclose(g.get_vecs_by_tokens("b").asnumpy(),
                                [3.0, 4.0], rtol=1e-6)
    # unknown catalogue name rejected before touching the filesystem
    with pytest.raises(KeyError):
        text.embedding.GloVe(pretrained_file_name="not_a_file.txt")
    # catalogued but missing locally: actionable error, no download
    with pytest.raises(ValueError, match="download"):
        text.embedding.GloVe(
            pretrained_file_name="glove.6B.100d.txt",
            embedding_root=str(root))
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names
    assert "wiki.simple.vec" in \
        text.embedding.get_pretrained_file_names("fasttext")
