"""Error-handling, sparse, and fft semantics (reference:
tests/python/unittest/test_exc_handling.py, test_sparse_ndarray.py,
test_numpy_op.py fft sections)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


# ---------------------------------------------------------------------------
# error semantics (reference test_exc_handling.py: async failures surface
# as Python exceptions, engine stays usable)
# ---------------------------------------------------------------------------

def test_backward_on_unrecorded_raises():
    x = mx.nd.ones((2,))
    with pytest.raises(MXNetError):
        x.backward()


def test_grad_of_non_attached_input():
    x = mx.nd.ones((2,))
    y = mx.nd.ones((2,))
    y.attach_grad()
    with mx.autograd.record():
        z = (x * y).sum()
    z.backward()
    assert y.grad is not None
    assert x.grad is None  # never attached: no gradient buffer


def test_shape_mismatch_is_python_exception():
    with pytest.raises(Exception):
        mx.nd.dot(mx.nd.ones((2, 3)), mx.nd.ones((4, 5))).wait_to_read()
    # framework still healthy afterwards
    out = mx.nd.dot(mx.nd.ones((2, 3)), mx.nd.ones((3, 2)))
    onp.testing.assert_allclose(out.asnumpy(), 3 * onp.ones((2, 2)))


def test_invalid_context_raises():
    with pytest.raises(MXNetError):
        mx.tpu(99)


def test_unknown_optimizer_raises():
    from mxnet_tpu.gluon import nn
    net = nn.Dense(1, in_units=1)
    net.initialize()
    with pytest.raises(MXNetError):
        mx.gluon.Trainer(net.collect_params(), "definitely_not_an_optimizer")


# ---------------------------------------------------------------------------
# sparse (reference test_sparse_ndarray.py)
# ---------------------------------------------------------------------------

def test_row_sparse_roundtrip_and_retain():
    from mxnet_tpu.ndarray import sparse
    dense = onp.zeros((6, 3), "float32")
    dense[1] = 1.0
    dense[4] = 2.0
    rs = sparse.row_sparse_array(
        (onp.array([[1., 1., 1.], [2., 2., 2.]], "float32"),
         onp.array([1, 4], "int64")), shape=(6, 3))
    assert rs.stype == "row_sparse"
    onp.testing.assert_allclose(rs.asdense().asnumpy(), dense)
    kept = rs.retain(mx.nd.array(onp.array([4], "int64")))
    d2 = kept.asdense().asnumpy()
    assert d2[1].sum() == 0 and d2[4].sum() == 6


def test_csr_roundtrip_and_dot():
    from mxnet_tpu.ndarray import sparse
    dense = onp.array([[0, 1, 0], [2, 0, 3]], "float32")
    csr = sparse.csr_matrix(
        (onp.array([1., 2., 3.], "float32"),
         onp.array([1, 0, 2], "int64"),
         onp.array([0, 1, 3], "int64")), shape=(2, 3))
    assert csr.stype == "csr"
    onp.testing.assert_allclose(csr.asdense().asnumpy(), dense)
    rhs = onp.array([[1.], [2.], [3.]], "float32")
    out = sparse.dot(csr, mx.nd.array(rhs))
    onp.testing.assert_allclose(out.asnumpy(), dense @ rhs)


def test_tostype_conversions():
    from mxnet_tpu.ndarray import sparse
    x = mx.nd.array(onp.array([[0, 1], [0, 0], [2, 0]], "float32"))
    rs = x.tostype("row_sparse") if hasattr(x, "tostype") \
        else sparse.row_sparse_array(x)
    onp.testing.assert_allclose(rs.asdense().asnumpy(), x.asnumpy())


# ---------------------------------------------------------------------------
# fft (reference numpy fft ops)
# ---------------------------------------------------------------------------

def test_fft_roundtrip_and_freqs():
    rng = onp.random.RandomState(0)
    x = rng.randn(16).astype("float32")
    X = mx.np.fft.fft(mx.np.array(x))
    onp.testing.assert_allclose(X.asnumpy(), onp.fft.fft(x),
                                rtol=1e-4, atol=1e-4)
    back = mx.np.fft.ifft(X)
    onp.testing.assert_allclose(back.asnumpy().real, x, rtol=1e-4,
                                atol=1e-4)
    onp.testing.assert_allclose(
        mx.np.fft.rfftfreq(8, d=0.5).asnumpy(), onp.fft.rfftfreq(8, 0.5))
    x2 = rng.randn(4, 8).astype("float32")
    onp.testing.assert_allclose(
        mx.np.fft.fft2(mx.np.array(x2)).asnumpy(), onp.fft.fft2(x2),
        rtol=1e-3, atol=1e-3)
