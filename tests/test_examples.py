"""Examples must stay runnable (reference CI runs example/ scripts)."""
import os
import runpy
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(name, argv=("x",)):
    old = sys.argv
    sys.argv = list(argv)
    try:
        runpy.run_path(os.path.join(REPO, "examples", name),
                       run_name="__main__")
    finally:
        sys.argv = old


def test_example_quantize():
    _run("quantize_inference.py")


@pytest.mark.slow
def test_example_ring_attention():
    # subprocess: the 8-virtual-device mesh needs XLA_FLAGS set before jax
    # initializes, which is impossible in this already-initialized process
    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu');"
         "import runpy, sys; sys.argv=['x'];"
         f"runpy.run_path(r'{os.path.join(REPO, 'examples', 'long_context_ring_attention.py')}',"
         "run_name='__main__')"],
        env=env, capture_output=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"ring attention over 8 devices" in r.stdout, r.stdout


@pytest.mark.slow
def test_example_mnist_one_epoch():
    # a full synthetic epoch (~10s subprocess) — slow tier; the quick
    # gate keeps the shorter example scripts below
    _run("train_mnist_gluon.py", ("x", "--epochs", "1"))


def test_example_sparse_embedding():
    _run("sparse_embedding_lm.py", ("x", "--vocab", "2000", "--steps", "8"))


def test_example_onnx_roundtrip(tmp_path):
    _run("onnx_export_import.py", ("x", "--out",
                                   str(tmp_path / "m.onnx")))


def test_example_moe_pipeline():
    # in-process: conftest already provisioned the 8-device CPU mesh
    _run("moe_pipeline_parallel.py")


@pytest.mark.slow
def test_example_lstm_lm():
    _run("train_lstm_lm.py", ("x", "--steps", "60"))


@pytest.mark.slow
def test_example_ssd():
    _run("ssd_detection.py", ("x", "--steps", "25", "--batch", "8"))


@pytest.mark.slow
def test_example_bert():
    _run("train_bert_classifier.py")


def test_opbench_runs_and_reports():
    """benchmark/opbench.py (reference benchmark/opperf analog): runs a
    filtered sweep and emits valid JSON with usec + gflops fields."""
    import json
    import subprocess
    import sys
    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark", "opbench.py"),
         "--iters", "3", "--warmup", "1", "--ops", "dot,relu"],
        cwd=REPO, env=env, timeout=300,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode()
    assert proc.returncode == 0, out[-2000:]
    lines = [json.loads(l) for l in out.splitlines()
             if l.startswith("{")]
    summary = lines[-1]
    assert summary["summary"] and summary["ops_measured"] >= 3
    per_op = lines[:-1]
    assert any(r["op"].startswith("dot_") and r["gflops"] > 0
               for r in per_op)


def test_example_pipeline_trainer():
    _run("pipeline_trainer.py", ("x", "--steps", "12", "--width", "16"))


@pytest.mark.slow
def test_example_convlstm():
    _run("convlstm_video.py", ("x", "--steps", "200"))


def test_example_wikitext_lm_pretrained_embedding():
    _run("wikitext_lm_pretrained_embedding.py", argv=("x", "--steps", "25"))
