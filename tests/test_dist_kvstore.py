"""Real multi-process dist kvstore tests.

Spawns 2 local worker processes through tools/launch.py (the reference's
`tools/launch.py -n N --launcher local` rig, reference
tests/nightly/test_distributed_training-gpu.sh:25-39) and verifies
KVStoreDist issues genuine cross-process collectives over the
jax.distributed runtime: broadcast-on-init, pushpull reduction, and
identical converged weights across workers.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_launch_local_two_process_dist_kvstore(tmp_path):
    worker = os.path.join(REPO, "tests", "dist_kvstore_worker.py")
    cmd = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
           "-n", "2", "--launcher", "local", "-p", str(_free_port()),
           sys.executable, worker, str(tmp_path)]
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # worker sets its own
    proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=600,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode("utf-8", "replace")
    assert proc.returncode == 0, f"launch failed:\n{out[-4000:]}"

    res = []
    for r in (0, 1):
        p = tmp_path / f"rank{r}.json"
        assert p.exists(), f"rank {r} wrote no result:\n{out[-4000:]}"
        res.append(json.loads(p.read_text()))
    r0, r1 = sorted(res, key=lambda d: d["rank"])

    # init broadcast: both ranks end with rank0's value
    onp.testing.assert_allclose(r0["init_bcast"], [10.0] * 4)
    onp.testing.assert_allclose(r1["init_bcast"], [10.0] * 4)
    # pushpull: 1s + 2s across processes -> 3s on BOTH ranks
    onp.testing.assert_allclose(r0["pushpull_sum"], [3.0] * 4)
    onp.testing.assert_allclose(r1["pushpull_sum"], [3.0] * 4)
    # sync training: both workers hold identical weights after 5 steps of
    # rank-distinct gradients (the dist_sync_kvstore.py invariant)
    onp.testing.assert_allclose(r0["trained_w"], r1["trained_w"], rtol=1e-6)
    # and the weights equal the serial computation over summed gradients
    rngs = [onp.random.RandomState(100), onp.random.RandomState(101)]
    w = onp.zeros(3, dtype="float32")
    for _ in range(5):
        g = sum(r.uniform(-1, 1, size=3).astype("float32") for r in rngs)
        w -= 0.1 * g
    onp.testing.assert_allclose(r0["trained_w"], w, rtol=1e-5)
    # async mode also reduced correctly
    onp.testing.assert_allclose(r0["async_sum"], [3.0] * 2)
    onp.testing.assert_allclose(r1["async_sum"], [3.0] * 2)
    # 2bit compression before the cross-process reduce: each rank emits
    # [±0.5, 0, ∓...] and error feedback re-emits held-back mass next round
    for r in (r0, r1):
        onp.testing.assert_allclose(r["compressed_round1"],
                                    [1.0, 0.0, -1.0, 0.0])
        onp.testing.assert_allclose(r["compressed_round2"],
                                    [1.0, 0.0, -1.0, 0.0])
    # fused multi-key pushpull: correct sums with >=5x fewer host syncs
    # than the per-key path (VERDICT r2 item 3 done-criterion)
    for r in (r0, r1):
        assert r["fused_sums_ok"]
        fused, perkey = r["fused_stats"], r["perkey_stats"]
        assert fused["blocks"] * 5 <= perkey["blocks"], (fused, perkey)
        assert fused["collectives"] * 5 <= perkey["collectives"], \
            (fused, perkey)
    # Trainer over dist_sync: identical weights across ranks (both
    # update_on_kvstore modes) and equal to the serial summed-grad run
    for key in ("trainer_w_updkv0", "trainer_w_updkv1"):
        for w0, w1 in zip(r0[key], r1[key]):
            onp.testing.assert_allclose(w0, w1, rtol=1e-5, atol=1e-6)
        for wd, ws in zip(r0[key], r0["trainer_w_serial"]):
            onp.testing.assert_allclose(wd, ws, rtol=1e-4, atol=1e-5)
