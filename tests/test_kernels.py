"""Pallas kernel layer (ops/kernels): dispatch gate, interpret-mode
parity sweep, grid-edge cases, fused optimizer bit-exactness, and the
guarded pipelined acceptance run.

The interpret tier (`pl.pallas_call(interpret=True)`) executes the
kernel BODIES as plain XLA ops on CPU — tier-1 exercises the kernels,
not just the XLA fallback. Parity contract (docs/PERF_NOTES.md
"Pallas kernel layer"): fp32 forwards are BIT-exact vs the references
for lane-aligned shapes; GRU/vanilla scan backwards and the optimizer
kernels are bit-exact too; the LSTM scan and norm backwards sit
within a few ulps (LLVM fp-contraction forms FMAs at different points
in structurally different programs); padded (unaligned) shapes get
tolerance-level parity because their reductions reassociate.
"""
import os

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.analysis import guard as tguard
from mxnet_tpu.gluon import Trainer, TrainLoop, nn, rnn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.ops import kernels as K
from mxnet_tpu.ops import rnn as rnn_ops
from mxnet_tpu.ops.kernels import norm as knorm
from mxnet_tpu.ops.kernels import opt_update as kopt
from mxnet_tpu.ops.kernels import rnn_scan as krnn
from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.telemetry import names as tnames

GATES = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}


def _rnn_args(mode, T=7, N=8, H=128, dtype="float32", seed=0):
    g = GATES[mode]
    r = onp.random.RandomState(seed)
    xw = jnp.asarray((r.randn(T, N, g * H) * 0.5).astype(dtype))
    h0 = jnp.asarray((r.randn(N, H) * 0.5).astype(dtype))
    c0 = jnp.asarray((r.randn(N, H) * 0.5).astype(dtype)) \
        if mode == "lstm" else None
    w = jnp.asarray((r.randn(g * H, H) * 0.3).astype(dtype))
    b = jnp.asarray((r.randn(g * H) * 0.1).astype(dtype))
    return xw, h0, c0, w, b


def _grads(fn, mode, rev, args):
    def loss(xw, h0, c0, w, b):
        ys, h, c = fn(xw, h0, c0, w, b, mode, reverse=rev)
        s = jnp.sum(ys * 0.3) + jnp.sum(h * 1.3)
        if c is not None:
            s = s + jnp.sum(c * 0.7)
        return s
    argn = (0, 1, 2, 3, 4) if mode == "lstm" else (0, 1, 3, 4)
    return jax.grad(loss, argnums=argn)(*args)


# ---------------------------------------------------------------------------
# dispatch gate
# ---------------------------------------------------------------------------

def test_pallas_mode_parsing(monkeypatch):
    for raw, want in (("", "auto"), ("auto", "auto"), ("1", "on"),
                      ("ON", "on"), ("force", "on"), ("0", "off"),
                      ("off", "off"), ("garbage", "auto")):
        monkeypatch.setenv("MXNET_PALLAS", raw)
        assert K.pallas_mode() == want
    monkeypatch.delenv("MXNET_PALLAS")
    assert K.pallas_mode() == "auto"


def test_dispatch_tiers_on_cpu(monkeypatch):
    monkeypatch.setenv("MXNET_PALLAS", "off")
    assert K.dispatch("rnn_scan")[0] == "xla"
    monkeypatch.setenv("MXNET_PALLAS", "auto")
    path, reason = K.dispatch("rnn_scan")
    assert path == "xla" and "non-TPU" in reason
    monkeypatch.setenv("MXNET_PALLAS", "on")
    path, reason = K.dispatch("rnn_scan")
    assert path == "interpret" and "interpret" in reason
    # unsupported cases force the XLA tier with the caller's reason
    path, reason = K.dispatch("rnn_scan", supported=False,
                              reason="f64 not kernelized")
    assert path == "xla" and reason == "f64 not kernelized"
    assert K.decisions()["rnn_scan"] == (path, reason)


def test_dispatch_table_covers_all_kernels(monkeypatch):
    monkeypatch.setenv("MXNET_PALLAS", "on")
    table = K.dispatch_table()
    assert set(table) == set(K.KERNELS)
    assert set(table.values()) == {"interpret"}
    monkeypatch.setenv("MXNET_PALLAS", "off")
    assert set(K.dispatch_table().values()) == {"xla"}


def test_dispatch_counts_in_telemetry(monkeypatch):
    monkeypatch.setenv("MXNET_PALLAS", "on")
    before = telemetry.value(tnames.KERNEL_DISPATCH, "interpret") or 0
    K.dispatch("layernorm")
    after = telemetry.value(tnames.KERNEL_DISPATCH, "interpret")
    assert after == before + 1


def test_scan_supported_reasons():
    xw, h0, c0, w, b = _rnn_args("lstm", T=3, N=4, H=16)
    assert krnn.scan_supported(xw, h0, c0, "lstm") is None
    assert "mode" in krnn.scan_supported(xw, h0, c0, "nope")
    assert "dtype" in krnn.scan_supported(
        xw.astype(jnp.float16), h0, c0, "lstm")


# ---------------------------------------------------------------------------
# RNN scan kernel: interpret-mode parity sweep
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh", "rnn_relu"])
@pytest.mark.parametrize("rev", [False, True])
def test_scan_fwd_bit_exact_f32(monkeypatch, mode, rev):
    """fp32 forward is BIT-identical to the lax.scan reference (lane-
    aligned shapes) — ys, h_T and c_T."""
    monkeypatch.setenv("MXNET_PALLAS", "on")
    args = _rnn_args(mode)
    ys_r, h_r, c_r = rnn_ops.scan_reference(*args, mode, reverse=rev)
    ys_k, h_k, c_k = krnn.rnn_scan(*args, mode, reverse=rev)
    assert bool((ys_r == ys_k).all())
    assert bool((h_r == h_k).all())
    assert (c_r is None) == (c_k is None)
    if c_r is not None:
        assert bool((c_r == c_k).all())


@pytest.mark.parametrize("mode", ["gru", "rnn_tanh", "rnn_relu"])
@pytest.mark.parametrize("rev", [False, True])
def test_scan_bwd_bit_exact_f32(monkeypatch, mode, rev):
    """GRU/vanilla backward is bit-identical too (the cotangent chain
    mirrors the scan transpose op for op)."""
    monkeypatch.setenv("MXNET_PALLAS", "on")
    args = _rnn_args(mode)
    gr = _grads(rnn_ops.scan_reference, mode, rev, args)
    gk = _grads(krnn.rnn_scan, mode, rev, args)
    for a, b in zip(gr, gk):
        assert bool((a == b).all())


@pytest.mark.parametrize("rev", [False, True])
def test_scan_bwd_lstm_ulp_parity(monkeypatch, rev):
    """The LSTM backward mirrors the scan transpose expression for
    expression, but LLVM fp-contraction differs across program
    structures — a few ulps, never more (docs/PERF_NOTES.md)."""
    monkeypatch.setenv("MXNET_PALLAS", "on")
    args = _rnn_args("lstm")
    gr = _grads(rnn_ops.scan_reference, "lstm", rev, args)
    gk = _grads(krnn.rnn_scan, "lstm", rev, args)
    for a, b in zip(gr, gk):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=5e-5)


@pytest.mark.pallas
@pytest.mark.parametrize("mode", ["lstm", "gru"])
@pytest.mark.parametrize("shape", [(5, 6, 50), (9, 3, 130)])
def test_scan_grid_edge_unaligned(monkeypatch, mode, shape):
    """Hidden not a multiple of the 128-lane width / batch off the
    sublane tile: the padded h2h dot contracts over extra zero lanes,
    so its reduction may reassociate — tolerance-level parity."""
    monkeypatch.setenv("MXNET_PALLAS", "on")
    T, N, H = shape
    args = _rnn_args(mode, T=T, N=N, H=H)
    ys_r, h_r, c_r = rnn_ops.scan_reference(*args, mode)
    ys_k, h_k, c_k = krnn.rnn_scan(*args, mode)
    onp.testing.assert_allclose(onp.asarray(ys_r), onp.asarray(ys_k),
                                rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(onp.asarray(h_r), onp.asarray(h_k),
                                rtol=1e-4, atol=1e-5)
    gr = _grads(rnn_ops.scan_reference, mode, False, args)
    gk = _grads(krnn.rnn_scan, mode, False, args)
    for a, b in zip(gr, gk):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=5e-5)


@pytest.mark.pallas
@pytest.mark.parametrize("mode,T", [("lstm", 10), ("gru", 10),
                                    ("lstm", 3), ("gru", 3)])
def test_scan_grid_edge_block_t(monkeypatch, mode, T):
    """Multi-timestep blocks with seq not divisible by (or smaller
    than) the block: the padded tail must contribute exact zeros."""
    monkeypatch.setenv("MXNET_PALLAS", "on")
    monkeypatch.setattr(krnn, "_FORCE_BLOCK_T", 4)
    args = _rnn_args(mode, T=T)
    ys_r, h_r, c_r = rnn_ops.scan_reference(*args, mode)
    ys_k, h_k, c_k = krnn.rnn_scan(*args, mode)
    onp.testing.assert_allclose(onp.asarray(ys_r), onp.asarray(ys_k),
                                rtol=1e-5, atol=1e-5)
    gr = _grads(rnn_ops.scan_reference, mode, False, args)
    gk = _grads(krnn.rnn_scan, mode, False, args)
    for a, b in zip(gr, gk):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=5e-5)


@pytest.mark.parametrize("mode", ["lstm", "gru"])
def test_scan_bf16_tolerance(monkeypatch, mode):
    monkeypatch.setenv("MXNET_PALLAS", "on")
    args = _rnn_args(mode, dtype="bfloat16")
    ys_r, h_r, c_r = rnn_ops.scan_reference(*args, mode)
    ys_k, h_k, c_k = krnn.rnn_scan(*args, mode)
    assert bool((ys_r == ys_k).all())      # fwd even bit-matches
    gr = _grads(rnn_ops.scan_reference, mode, False, args)
    gk = _grads(krnn.rnn_scan, mode, False, args)
    for a, b in zip(gr, gk):
        onp.testing.assert_allclose(
            onp.asarray(a, onp.float32), onp.asarray(b, onp.float32),
            rtol=0.05, atol=0.5)


def test_fused_rnn_layer_parity_through_gate(monkeypatch):
    """The gluon LSTM layer end to end: MXNET_PALLAS=on output equals
    the off (reference) output bit for bit at aligned dims. One net —
    the dispatch decision is read per call."""
    r = onp.random.RandomState(0)
    x = r.randn(5, 4, 32).astype("float32")
    net = rnn.LSTM(128, num_layers=2, bidirectional=True,
                   input_size=32)
    net.initialize()
    outs = {}
    for env in ("off", "on"):
        monkeypatch.setenv("MXNET_PALLAS", env)
        outs[env] = net(mx.nd.array(x)).asnumpy()
    assert bool((outs["off"] == outs["on"]).all())


def test_scan_residual_bytes_ratchet(monkeypatch):
    """THE point of the kernel: the backward saves only the hidden
    (+cell) trajectory instead of the scan's per-step residual
    streams. Strictly fewer residual bytes at the LSTM-leg shape —
    the backend-independent form of 'fewer HBM round-trips' (the
    interpret-mode HLO's while-carries make raw boundary_bytes
    incomparable on CPU; see docs/PERF_NOTES.md)."""
    T, N, H, C = 35, 16, 128, 128
    r = onp.random.RandomState(0)
    x = jnp.asarray(r.randn(T, N, C).astype("f4"))
    h0 = jnp.asarray(r.randn(N, H).astype("f4"))
    c0 = jnp.asarray(r.randn(N, H).astype("f4"))
    wih = jnp.asarray((r.randn(4 * H, C) * 0.2).astype("f4"))
    whh = jnp.asarray((r.randn(4 * H, H) * 0.2).astype("f4"))
    bih = jnp.asarray((r.randn(4 * H) * 0.1).astype("f4"))
    bhh = jnp.asarray((r.randn(4 * H) * 0.1).astype("f4"))

    def measure(env):
        monkeypatch.setenv("MXNET_PALLAS", env)

        def f(x, h0, c0, wih, whh, bih, bhh):
            y, _, _ = rnn_ops._one_direction(
                x, h0, c0, wih, whh, bih, bhh, "lstm", False)
            return y
        _, vjp = jax.vjp(f, x, h0, c0, wih, whh, bih, bhh)
        return sum(int(l.nbytes) for l in jax.tree_util.tree_leaves(vjp)
                   if hasattr(l, "nbytes"))

    ref, ker = measure("off"), measure("on")
    assert ker < ref, (ker, ref)
    assert ref / ker > 1.5          # ~13 streams -> ys+cs (+inputs)


# ---------------------------------------------------------------------------
# LayerNorm / bias-GELU kernels
# ---------------------------------------------------------------------------

def test_layernorm_fwd_bit_exact_aligned():
    r = onp.random.RandomState(0)
    x = jnp.asarray(r.randn(4, 16, 256).astype("f4"))
    g = jnp.asarray(r.randn(256).astype("f4"))
    b = jnp.asarray(r.randn(256).astype("f4"))

    def ref(x, g, b):
        from jax import lax
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return ((x - mean) * lax.rsqrt(var + 1e-5)
                * g.reshape(1, 1, -1) + b.reshape(1, 1, -1))

    a = jax.jit(ref)(x, g, b)
    k = jax.jit(lambda x, g, b: knorm.layer_norm(
        x, g, b, interpret=True))(x, g, b)
    assert bool((a == k).all())


@pytest.mark.pallas
@pytest.mark.parametrize("shape", [(8, 100), (3, 5, 130), (16, 256)])
def test_layernorm_fwd_bwd_tolerance(shape):
    c = shape[-1]
    r = onp.random.RandomState(1)
    x = jnp.asarray(r.randn(*shape).astype("f4"))
    g = jnp.asarray(r.randn(c).astype("f4"))
    b = jnp.asarray(r.randn(c).astype("f4"))
    from mxnet_tpu.ops import nn as FNN
    ref = FNN.layer_norm(x, g, b)          # default env: XLA reference
    ker = knorm.layer_norm(x, g, b, interpret=True)
    onp.testing.assert_allclose(onp.asarray(ref), onp.asarray(ker),
                                rtol=1e-5, atol=1e-5)
    gr = jax.grad(lambda *a: jnp.sum(jnp.cos(FNN.layer_norm(*a))),
                  argnums=(0, 1, 2))(x, g, b)
    gk = jax.grad(lambda *a: jnp.sum(jnp.cos(knorm.layer_norm(
        *a, interpret=True))), argnums=(0, 1, 2))(x, g, b)
    for a, bb in zip(gr, gk):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(bb),
                                    rtol=2e-3, atol=1e-4)


def test_layer_norm_op_dispatches(monkeypatch):
    """ops/nn.py layer_norm routes through the kernel under the gate
    (and the gluon LayerNorm block with it) — outputs stay equal."""
    from mxnet_tpu.ops import nn as FNN
    r = onp.random.RandomState(2)
    x = jnp.asarray(r.randn(6, 256).astype("f4"))
    g = jnp.asarray(r.randn(256).astype("f4"))
    b = jnp.asarray(r.randn(256).astype("f4"))
    monkeypatch.setenv("MXNET_PALLAS", "off")
    ref = FNN.layer_norm(x, g, b)
    monkeypatch.setenv("MXNET_PALLAS", "on")
    ker = FNN.layer_norm(x, g, b)
    assert K.decisions()["layernorm"][0] == "interpret"
    onp.testing.assert_allclose(onp.asarray(ref), onp.asarray(ker),
                                rtol=1e-6, atol=1e-6)
    # non-trailing axis stays on the reference path
    FNN.layer_norm(x, jnp.ones(6), jnp.zeros(6), axis=0)


def test_bias_gelu_fwd_bit_exact_and_bwd():
    r = onp.random.RandomState(3)
    x = jnp.asarray(r.randn(4, 16, 256).astype("f4"))
    b = jnp.asarray(r.randn(256).astype("f4"))
    ref = jax.nn.gelu(x + b, approximate=False)
    ker = knorm.bias_gelu(x, b, interpret=True)
    assert bool((ref == ker).all())
    gr = jax.grad(lambda x, b: jnp.sum(jnp.cos(jax.nn.gelu(
        x + b, approximate=False))), argnums=(0, 1))(x, b)
    gk = jax.grad(lambda x, b: jnp.sum(jnp.cos(knorm.bias_gelu(
        x, b, interpret=True))), argnums=(0, 1))(x, b)
    for a, bb in zip(gr, gk):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(bb),
                                    rtol=2e-4, atol=1e-5)


def test_positionwise_ffn_bias_gelu_wiring(monkeypatch):
    """PositionwiseFFN takes the fused bias-GELU path under the gate,
    with output parity against the Dense→Activation reference."""
    from mxnet_tpu.gluon.nn.transformer import PositionwiseFFN
    r = onp.random.RandomState(4)
    x = r.randn(2, 6, 64).astype("f4")
    ffn = PositionwiseFFN(64, 256)
    ffn.initialize()
    outs = {}
    for env in ("off", "on"):
        monkeypatch.setenv("MXNET_PALLAS", env)
        assert (ffn._bias_gelu_path(mx.nd.array(x)) is not None) \
            == (env == "on")
        outs[env] = ffn(mx.nd.array(x)).asnumpy()
    onp.testing.assert_allclose(outs["off"], outs["on"],
                                rtol=1e-5, atol=1e-6)


def test_flash_attention_through_gate(monkeypatch):
    """flash_attention's default path obeys the shared gate: interpret
    kernels when forced on CPU, with parity vs the XLA blockwise path."""
    from mxnet_tpu.ops.attention import flash_attention
    r = onp.random.RandomState(5)
    q = jnp.asarray(r.randn(1, 2, 64, 64).astype("f4"))
    k = jnp.asarray(r.randn(1, 2, 64, 64).astype("f4"))
    v = jnp.asarray(r.randn(1, 2, 64, 64).astype("f4"))
    monkeypatch.setenv("MXNET_PALLAS", "off")
    ref = flash_attention(q, k, v, causal=True)
    monkeypatch.setenv("MXNET_PALLAS", "on")
    ker = flash_attention(q, k, v, causal=True)
    assert K.decisions()["flash_attention"][0] == "interpret"
    onp.testing.assert_allclose(onp.asarray(ref), onp.asarray(ker),
                                rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# fused optimizer-update kernel
# ---------------------------------------------------------------------------

def _opt_case(kind):
    if kind == "sgd":
        cfg = {"momentum": 0.9, "has_clip": False}

        def ref(w, g, lr, wd, t, states, rescale):
            g = g * rescale
            g = g + wd * w
            m = 0.9 * states[0] - lr * g
            return w + m, (m,)
        n_states = 1
    else:
        cfg = {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
               "has_clip": False}

        def ref(w, g, lr, wd, t, states, rescale):
            b1, b2, eps = 0.9, 0.999, 1e-8
            m, v = states
            g = g * rescale
            g = g + wd * w
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mhat = m / (1 - b1 ** t)
            vhat = v / (1 - b2 ** t)
            return w - lr * mhat / (jnp.sqrt(vhat) + eps), (m, v)
        n_states = 2
    return cfg, ref, n_states


@pytest.mark.parametrize("kind", ["sgd", "adam"])
@pytest.mark.parametrize("hp", ["scalar", "vector"])
def test_opt_update_bit_exact(kind, hp):
    """The kernel applies the literal rule expressions on a reshaped
    lane layout — bit-exact vs the XLA elementwise chain, for scalar
    AND per-element (pack_shard_hparams bucket) hyperparameters."""
    cfg, ref, n_states = _opt_case(kind)
    r = onp.random.RandomState(0)
    P = 5000
    w = jnp.asarray(r.randn(P).astype("f4"))
    g = jnp.asarray(r.randn(P).astype("f4"))
    states = tuple(jnp.asarray(abs(r.randn(P)).astype("f4") * 0.1)
                   for _ in range(n_states))
    rescale = jnp.float32(0.25)
    if hp == "scalar":
        lr, wd, t = jnp.float32(0.05), jnp.float32(0.01), jnp.int32(3)
    else:
        lr = jnp.asarray(r.rand(P).astype("f4") * 0.1)
        wd = jnp.asarray(r.rand(P).astype("f4") * 0.01)
        t = jnp.asarray(r.randint(1, 5, P).astype("i4"))

    @jax.jit
    def both(w, g, lr, wd, t, states):
        a = ref(w, g, lr, wd, t, states, rescale)
        b = kopt.unit_update(kind, cfg, w, g, lr, wd, t, rescale,
                             jnp.float32(0.0), states, interpret=True)
        return a, b

    (wr, sr), (wk, sk) = both(w, g, lr, wd, t, states)
    assert bool((wr == wk).all())
    for a, b in zip(sr, sk):
        assert bool((a == b).all())


@pytest.mark.parametrize("kind", ["sgd", "adam"])
def test_opt_update_bit_exact_dp4_sharded(kind):
    """The acceptance claim on REAL ZeRO layout: a NamedSharding'd
    flat 1/N-per-replica buffer at dp=4 (nonzero moments) updates
    bit-identically through the kernel and the XLA chain."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    cfg, ref, n_states = _opt_case(kind)
    mesh = Mesh(onp.array(jax.devices()[:4]), ("dp",))
    shard = NamedSharding(mesh, P("dp"))
    r = onp.random.RandomState(1)
    Pn = 4096
    w = jax.device_put(jnp.asarray(r.randn(Pn).astype("f4")), shard)
    g = jax.device_put(jnp.asarray(r.randn(Pn).astype("f4")), shard)
    states = tuple(jax.device_put(
        jnp.asarray(abs(r.randn(Pn)).astype("f4") * 0.1), shard)
        for _ in range(n_states))
    rescale = jnp.float32(0.25)

    @jax.jit
    def both(w, g, states):
        a = ref(w, g, jnp.float32(0.05), jnp.float32(0.01),
                jnp.int32(3), states, rescale)
        b = kopt.unit_update(kind, cfg, w, g, jnp.float32(0.05),
                             jnp.float32(0.01), jnp.int32(3), rescale,
                             jnp.float32(0.0), states, interpret=True)
        return a, b

    (wr, sr), (wk, sk) = both(w, g, states)
    for a, b in zip(sr, sk):
        assert bool((a == b).all())       # states bit-exact, always
    if kind == "adam":
        assert bool((wr == wk).all())
    else:
        # sgd-mom at dp=4: XLA duplicates the momentum expression
        # into the weight fusion and fp-contracts the copy (it strips
        # optimization barriers on CPU, so this is not preventable
        # in-program) — the weight sits within 1 ulp of w + m
        onp.testing.assert_allclose(onp.asarray(wr), onp.asarray(wk),
                                    rtol=0, atol=1e-8)


def test_opt_kernel_kind_gating():
    from mxnet_tpu import optimizer as opt_mod
    assert kopt.opt_kernel_kind(opt_mod.SGD(momentum=0.9))[0] == "sgd"
    assert kopt.opt_kernel_kind(opt_mod.Adam())[0] == "adam"
    # LAMB is non-elementwise; subclass rules are not kernelized
    assert kopt.opt_kernel_kind(opt_mod.create("lamb")) is None
    assert kopt.opt_kernel_kind(opt_mod.create("nag")) is None


def test_kernel_step_fn_respects_gate(monkeypatch):
    from mxnet_tpu import optimizer as opt_mod
    monkeypatch.setenv("MXNET_PALLAS", "off")
    assert opt_mod.Adam().kernel_step_fn() is None
    monkeypatch.setenv("MXNET_PALLAS", "on")
    assert opt_mod.Adam().kernel_step_fn() is not None
    assert opt_mod.create("nag").kernel_step_fn() is None


def _zero_step(optname, kw, seed=0):
    mx.random.seed(seed)
    onp.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(8))
    net.initialize()
    r = onp.random.RandomState(seed)
    x = mx.nd.array(r.randn(16, 12).astype("float32"))
    y = mx.nd.array(r.randint(0, 8, size=(16,)).astype("int32"))
    net(x)
    loss = gloss.SoftmaxCrossEntropyLoss()
    tr = Trainer(net.collect_params(), optname, kw, kvstore=None)
    mesh = make_mesh({"dp": 4}, jax.devices()[:4])
    step = tr.compile_step(lambda a, b: loss(net(a), b), mesh=mesh,
                           zero_shard=True)
    return net, step, x, y


@pytest.mark.parametrize("optname,kw", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-3})])
def test_zero_train_step_kernel_vs_xla(monkeypatch, optname, kw):
    """The full zero-sharded train step at dp=4, kernel vs XLA update:
    bit-exact params and state after the first application, and
    ulp-level (the whole-program fp-contraction noise, ~1e-8
    relative) over a 4-step trajectory with equal losses."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    results = {}
    for env in ("off", "on"):
        monkeypatch.setenv("MXNET_PALLAS", env)
        net, step, x, y = _zero_step(optname, kw)
        losses = []
        snaps = []
        for _ in range(4):
            losses.append(float(step(x, y).asnumpy().sum()))
            snaps.append({k: p.data().asnumpy()
                          for k, p in net.collect_params().items()})
        results[env] = (losses, snaps)
    (l_off, s_off), (l_on, s_on) = results["off"], results["on"]
    for k in s_off[0]:
        if optname == "adam":
            assert bool((s_off[0][k] == s_on[0][k]).all()), k
        else:   # sgd-mom: ±1 ulp (see test_opt_update_bit_exact_dp4)
            onp.testing.assert_allclose(s_off[0][k], s_on[0][k],
                                        rtol=0, atol=1e-7)
    for a, b in zip(l_off, l_on):
        assert abs(a - b) < 1e-4
    for k in s_off[-1]:
        onp.testing.assert_allclose(s_off[-1][k], s_on[-1][k],
                                    rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# the acceptance run: pipelined + guarded, kernels ON, zero unblessed syncs
# ---------------------------------------------------------------------------

def test_guarded_12step_pipelined_kernels_on(monkeypatch):
    """12 pipelined steps of an LSTM model with every kernel on the
    interpret tier under MXNET_TRANSFER_GUARD=raise: the kernel layer
    introduces no host syncs (interpret bodies are pure XLA ops)."""
    monkeypatch.setenv("MXNET_PALLAS", "on")
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    onp.random.seed(0)

    class TinyLM(mx.gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(16, 8)
            self.lstm = rnn.LSTM(16, num_layers=1, layout="NTC")
            self.head = nn.Dense(16, flatten=False)

        def forward(self, tokens):
            return self.head(self.lstm(self.emb(tokens)))

    net = TinyLM()
    net.initialize()
    r = onp.random.RandomState(0)
    x = mx.nd.array(r.randint(0, 16, size=(4, 8)).astype("int32"))
    y = mx.nd.array(r.randint(0, 16, size=(4, 8)).astype("int32"))
    net(x)
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 5e-3})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=2)
    loop.step(x, y)                  # compile outside the counted region
    loop.synchronize()
    tguard.reset_sync_counts()
    for bx, by in loop.prefetch((x, y) for _ in range(12)):
        loop.step(bx, by)            # raises on any unblessed sync
    loop.synchronize()
    counts = tguard.sync_counts()
    assert counts.get("wait_to_read", 0) == 0
    assert counts.get("window_retire", 0) == 12
    # the scan kernel actually took the interpret tier in this program
    assert K.decisions()["rnn_scan"][0] == "interpret"
