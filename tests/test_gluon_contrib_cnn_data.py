"""gluon.contrib cnn Blocks + data tail.

Reference analogs: tests/python/unittest/test_gluon_contrib.py
(DeformableConvolution block tests), gluon/contrib/data/sampler.py
doctest, gluon/contrib/data/text.py datasets.
"""
import collections

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import contrib as gcontrib
from mxnet_tpu.gluon import nn


# ---------------------------------------------------------------------------
# DeformableConvolution / ModulatedDeformableConvolution blocks
# ---------------------------------------------------------------------------

def test_deformable_conv_zero_offset_equals_conv():
    """Offset net initializes to zeros, so DCNv1 == plain convolution."""
    onp.random.seed(0)
    x = nd.array(onp.random.randn(2, 4, 10, 10).astype("float32"))

    dcn = gcontrib.cnn.DeformableConvolution(
        8, kernel_size=3, padding=1, in_channels=4)
    dcn.initialize()
    out = dcn(x)
    assert out.shape == (2, 8, 10, 10)

    ref = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=4)
    ref.initialize()
    ref.weight.set_data(dcn.deformable_conv_weight.data())
    ref.bias.set_data(dcn.deformable_conv_bias.data())
    onp.testing.assert_allclose(out.asnumpy(), ref(x).asnumpy(),
                                rtol=1e-4, atol=1e-4)


def test_modulated_deformable_conv_zero_init():
    """At zero init the mask is 2*sigmoid(0)=1, so DCNv2 also reduces
    to the plain convolution (reference conv_layers.py:381 scaling)."""
    onp.random.seed(1)
    x = nd.array(onp.random.randn(2, 3, 8, 8).astype("float32"))
    dcn = gcontrib.cnn.ModulatedDeformableConvolution(
        6, kernel_size=3, padding=1, in_channels=3)
    dcn.initialize()
    out = dcn(x)
    assert out.shape == (2, 6, 8, 8)

    ref = nn.Conv2D(6, kernel_size=3, padding=1, in_channels=3)
    ref.initialize()
    ref.weight.set_data(dcn.deformable_conv_weight.data())
    ref.bias.set_data(dcn.deformable_conv_bias.data())
    onp.testing.assert_allclose(out.asnumpy(), ref(x).asnumpy(),
                                rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("cls", [gcontrib.cnn.DeformableConvolution,
                                 gcontrib.cnn.ModulatedDeformableConvolution])
def test_deformable_conv_grads_flow(cls):
    onp.random.seed(2)
    net = cls(5, kernel_size=3, padding=1, num_deformable_group=1,
              activation="relu")
    net.initialize()
    x = nd.array(onp.random.randn(2, 4, 6, 6).astype("float32"))
    with autograd.record():
        y = net(x)
        loss = (y ** 2).mean()
    loss.backward()
    gw = net.deformable_conv_weight.grad().asnumpy()
    gow = net.offset_weight.grad().asnumpy()
    assert onp.isfinite(gw).all() and onp.abs(gw).sum() > 0
    # offset weights start at zero but must receive gradient through the
    # bilinear sampling coordinates
    assert onp.isfinite(gow).all() and onp.abs(gow).sum() > 0


def test_deformable_conv_deferred_init_and_repr():
    net = gcontrib.cnn.DeformableConvolution(7, kernel_size=(3, 3),
                                             padding=(1, 1))
    net.initialize()
    x = nd.array(onp.zeros((1, 5, 9, 9), "float32"))
    y = net(x)
    assert y.shape == (1, 7, 9, 9)
    assert net.deformable_conv_weight.shape == (7, 5, 3, 3)
    assert "5 -> 7" in repr(net)


def test_deformable_conv_nonzero_offset_differs():
    """With a real offset field the result must differ from the plain
    conv (the sampling grid actually moved)."""
    onp.random.seed(3)
    dcn = gcontrib.cnn.DeformableConvolution(4, kernel_size=3, padding=1,
                                             in_channels=4)
    dcn.initialize()
    # push the offset weights away from zero
    dcn.offset_weight.set_data(
        nd.array(onp.random.randn(
            *dcn.offset_weight.shape).astype("float32") * 0.5))
    x = nd.array(onp.random.randn(1, 4, 8, 8).astype("float32"))
    ref = nn.Conv2D(4, kernel_size=3, padding=1, in_channels=4)
    ref.initialize()
    ref.weight.set_data(dcn.deformable_conv_weight.data())
    ref.bias.set_data(dcn.deformable_conv_bias.data())
    assert onp.abs(dcn(x).asnumpy() - ref(x).asnumpy()).max() > 1e-3


# ---------------------------------------------------------------------------
# contrib.data: IntervalSampler + WikiText
# ---------------------------------------------------------------------------

def test_interval_sampler_reference_examples():
    s = gcontrib.data.IntervalSampler(13, interval=3)
    assert list(s) == [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]
    assert len(s) == 13
    s = gcontrib.data.IntervalSampler(13, interval=3, rollover=False)
    assert list(s) == [0, 3, 6, 9, 12]
    assert len(s) == 5


def test_wikitext2_synthetic_fallback(tmp_path):
    ds = gcontrib.data.WikiText2(root=str(tmp_path), seq_len=7)
    assert ds.source == "synthetic"
    assert len(ds) > 0
    data, label = ds[0]
    assert data.shape == (7,) and label.shape == (7,)
    # label is data shifted by one position in the flat stream
    d2, _ = ds[1]
    flat_data = onp.concatenate([ds[i][0].asnumpy()
                                 for i in range(len(ds))])
    flat_label = onp.concatenate([ds[i][1].asnumpy()
                                  for i in range(len(ds))])
    onp.testing.assert_array_equal(flat_data[1:], flat_label[:-1])
    # vocabulary built from corpus, has <eos> reserved
    assert "<eos>" in ds.vocabulary.token_to_idx


def test_wikitext_file_source_and_custom_vocab(tmp_path):
    content = "hello world\nhello again\n"
    (tmp_path / "wiki.valid.tokens").write_text(content, encoding="utf8")
    ds = gcontrib.data.WikiText2(root=str(tmp_path), segment="validation",
                                 seq_len=2)
    assert ds.source == "file"
    toks = ds.vocabulary.to_tokens(
        [int(i) for i in ds[0][0].asnumpy().tolist()])
    assert toks[0] == "hello"
    assert ds.frequencies["hello"] == 2
    # explicit vocabulary is honored, not rebuilt
    from mxnet_tpu.contrib import text
    v = text.Vocabulary(collections.Counter(["hello", "world"]),
                        reserved_tokens=["<eos>"])
    ds2 = gcontrib.data.WikiText2(root=str(tmp_path),
                                  segment="validation", vocab=v,
                                  seq_len=2)
    assert ds2.vocabulary is v
    with pytest.raises(ValueError):
        gcontrib.data.WikiText2(root=str(tmp_path), segment="bogus")


def test_wikitext103_constructs(tmp_path):
    ds = gcontrib.data.WikiText103(root=str(tmp_path), segment="test",
                                   seq_len=5)
    assert ds.source == "synthetic" and len(ds) > 0
