"""Continuous-batching decode engine (docs/SERVING.md "Continuous
batching").

Pins the iteration-level-scheduling contracts:

- fake-clock join/leave: requests enter and exit the running batch
  BETWEEN decode steps, a freed slot is refilled from the queue on the
  next iteration;
- BIT-EXACT token parity: a request decoded continuously next to
  batch-mates produces the identical token sequence it produces alone
  (masked carries + the null page make neighbours invisible);
- chunked prefill never starves the decode batch (strict alternation);
- KV-page exhaustion sheds with a typed ``Overloaded(reason="kvcache")``
  and allocator bytes == census bytes (one accounting path);
- the guarded zero-sync streamed run: 12+ iterations under
  MXNET_TRANSFER_GUARD=raise with the retire as the ONE blessed sync;
- the decode program passes the full static-analysis lint with
  ``predict`` expectations;
- rnn_decode_step interpret-vs-XLA parity across all four cell modes.
"""
import numpy as onp
import pytest

import jax.numpy as jnp

from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (DecodeEngine, DecodeStream, Overloaded,
                               PagedKVCache, TinyDecoder, pages_needed)
from mxnet_tpu.serving.resilience import (DeadlineExceeded,
                                          ServingShutdown)

VOCAB = 48


@pytest.fixture(scope="module")
def model():
    return TinyDecoder(vocab=VOCAB, d_model=32, num_heads=2, seed=0)


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float):
        self.t += dt


def make_engine(model, **kw):
    kw.setdefault("ladder", (1, 2))
    kw.setdefault("page_size", 4)
    kw.setdefault("max_context", 32)
    kw.setdefault("start", False)
    return DecodeEngine(model, **kw)


def drive(eng, max_iters: int = 200) -> int:
    """Manually run the scheduler to completion (start=False engines)."""
    it = 0
    while it < max_iters:
        did = eng.step_once()
        eng.sync()
        if not did and eng._idle():
            return it
        it += 1
    raise AssertionError(f"engine did not go idle in {max_iters} iters")


def prompt(seed: int, n: int):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, size=n).astype(onp.int32)


# ---------------------------------------------------------------------------
# accessors + tunables
# ---------------------------------------------------------------------------

def test_slot_ladder_env_override(monkeypatch):
    monkeypatch.setenv("MXNET_DECODE_SLOTS", "2, 8,4")
    assert serving.slot_ladder() == (2, 4, 8)
    monkeypatch.setenv("MXNET_DECODE_SLOTS", "garbage")
    assert serving.slot_ladder() == serving.decode.DECODE_SLOT_LADDER


def test_page_size_and_chunk_env_overrides(monkeypatch):
    monkeypatch.setenv("MXNET_DECODE_KV_PAGE_SIZE", "8")
    monkeypatch.setenv("MXNET_DECODE_PREFILL_CHUNK", "32")
    assert serving.kv_page_size() == 8
    assert serving.prefill_chunk() == 32


def test_decode_tunables_registered():
    from mxnet_tpu.tuning import space
    names = {t["name"]: t for t in space.table()}
    for name in ("decode.slot_ladder", "decode.kv_page_size",
                 "decode.prefill_chunk"):
        assert name in names, name
        assert names[name]["scope"] == "serving"
        assert "decode" in names[name]["seam"]
    assert names["decode.kv_page_size"]["grid"] == (8, 16, 32, 64)


def test_kv_page_size_validity_respects_memory_budget(monkeypatch):
    from mxnet_tpu.serving.decode import _page_size_valid
    assert _page_size_valid(16, None)
    assert not _page_size_valid(0, None)
    assert not _page_size_valid("x", None)
    # a 16 KiB budget cannot hold the nominal full cache at ANY page
    # size, so every candidate is invalid under it
    monkeypatch.setenv("MXNET_MEMORY_BUDGET", str(16 * 1024))
    assert not _page_size_valid(16, None)


# ---------------------------------------------------------------------------
# paged KV cache
# ---------------------------------------------------------------------------

def test_kvcache_null_page_reserved_and_freelist():
    kv = PagedKVCache(1, 2, 16, num_pages=5, page_size=4)
    assert kv.free_pages() == 4          # page 0 excluded
    a, b = object(), object()
    pa = kv.alloc(a, 3)
    assert pa is not None and 0 not in pa
    assert kv.alloc(b, 2) is None        # only 1 left
    pb = kv.alloc(b, 1)
    assert pb is not None and kv.free_pages() == 0
    assert kv.release(a) == 3
    assert kv.free_pages() == 3
    assert kv.used_pages() == 1 and kv.pages_of(b) == pb


def test_kvcache_reserve_excludes_pages_from_admission():
    kv = PagedKVCache(1, 2, 16, num_pages=5, page_size=4)
    a, b = object(), object()
    assert kv.reserve(a, 3)
    assert not kv.can_reserve(2)         # 4 - 3 reserved = 1 free
    assert not kv.reserve(b, 2)
    pages = kv.alloc(a, 3)               # draws down the reservation
    assert len(pages) == 3 and kv.free_pages() == 1
    assert kv.reserve(b, 1)


def test_kvcache_allocator_bytes_equal_census_bytes():
    """ONE accounting path: the allocator prices its pages with the
    census's device_bytes rule, so the kvcache pool's census bytes grow
    by exactly PagedKVCache.total_bytes()."""
    import gc
    gc.collect()
    census = telemetry.memory.census()
    before = census.live_bytes_by_pool()["kvcache"]
    kv = PagedKVCache(1, 2, 16, num_pages=9, page_size=8)
    after = census.live_bytes_by_pool()["kvcache"]
    assert after - before == kv.total_bytes()
    assert kv.total_bytes() == \
        2 * (9 * 8 * 2 * 16) * 4         # K+V, f32
    assert kv.total_bytes() == kv.bytes_per_page * kv.num_pages


def test_pages_needed():
    assert pages_needed(1, 4) == 1
    assert pages_needed(4, 4) == 1
    assert pages_needed(5, 4) == 2
    assert pages_needed(0, 4) == 1       # floor: every request holds >=1


# ---------------------------------------------------------------------------
# iteration-level scheduling (fake clock, manual drive)
# ---------------------------------------------------------------------------

def test_join_leave_between_steps(model):
    """3 requests, 2 slots: the queued request joins the running batch
    the iteration after a finisher leaves — nobody waits for the whole
    batch to retire."""
    clk = FakeClock()
    eng = make_engine(model, clock=clk)
    try:
        sa = eng.submit(prompt(1, 3), max_new=6)
        sb = eng.submit(prompt(2, 2), max_new=2)
        sc = eng.submit(prompt(3, 2), max_new=3)
        # first refill seats A and B; C waits in the queue
        eng.step_once()
        assert [o is not None for o in eng._occupant] == [True, True]
        assert len(eng._queue) == 1
        joined_at = None
        for it in range(60):
            clk.advance(0.001)
            did = eng.step_once()
            eng.sync()
            occ = [getattr(o, "seq", None) for o in eng._occupant]
            if joined_at is None and 2 in occ:
                joined_at = it
                assert sb.done       # C sits in B's freed slot
            if not did and eng._idle():
                break
        assert joined_at is not None, "queued request never joined"
        assert len(sa.result(0)) == 6
        assert len(sb.result(0)) == 2
        assert len(sc.result(0)) == 3
        assert eng.stats["completed"] == 3
        assert eng.kv.used_pages() == 0 and eng.kv.free_pages() > 0
    finally:
        eng.close()


def test_fake_clock_ttft_and_stream_record(model):
    clk = FakeClock(t=50.0)
    eng = make_engine(model, ladder=(1,), clock=clk)
    try:
        s = eng.submit(prompt(4, 2), max_new=3)
        while not s.done:
            clk.advance(0.25)
            eng.step_once()
            eng.sync()
        rec = s.record()
        assert rec["outcome"] == "ok" and rec["tokens"] == 3
        # prefill(1 chunk) retires 0.25s after submit on the fake clock
        assert rec["ttft_s"] == pytest.approx(0.25)
        assert rec["tpot_s"] == pytest.approx([0.25, 0.25])
        assert s.ttft_s == pytest.approx(0.25)
    finally:
        eng.close()


def test_stream_next_token_iteration_and_result(model):
    eng = make_engine(model, ladder=(1,))
    try:
        s = eng.submit(prompt(5, 2), max_new=4)
        drive(eng)
        toks = [t for t in s]
        assert len(toks) == 4
        assert s.result(0) == toks
        assert s.next_token(0) is None   # cursor stays at end-of-stream
        assert all(0 <= t < VOCAB for t in toks)
    finally:
        eng.close()


def test_eos_frees_slot_early(model):
    """An EOS hit retires the request before max_new and releases its
    pages immediately."""
    eng = make_engine(model, ladder=(1,))
    try:
        s = eng.submit(prompt(6, 3), max_new=20)
        first = None
        while first is None:
            eng.step_once()
            eng.sync()
            r = s.record()
            if r["tokens"]:
                first = r
        # resubmit with eos = the first generated token: exactly 1 token
        drive(eng)
        tok0 = s.result(0)[0]
        s2 = eng.submit(prompt(6, 3), max_new=20, eos=int(tok0))
        drive(eng)
        assert s2.result(0) == [tok0]
        assert eng.kv.used_pages() == 0
    finally:
        eng.close()


def test_deadline_miss_is_typed(model):
    clk = FakeClock()
    eng = make_engine(model, ladder=(1,), clock=clk)
    try:
        s = eng.submit(prompt(7, 2), max_new=8, deadline_ms=100.0)
        clk.advance(10.0)                # way past the deadline
        drive(eng)
        with pytest.raises(DeadlineExceeded):
            s.result(0)
        assert eng.stats["deadline_missed"] == 1
        assert eng.kv.used_pages() == 0  # pages released on failure
    finally:
        eng.close()


def test_drain_sheds_then_close_is_shutdown(model):
    eng = make_engine(model, ladder=(1,))
    try:
        s = eng.submit(prompt(8, 2), max_new=2)
        assert eng.drain()
        assert s.result(0) and s.done
        with pytest.raises(Overloaded) as ei:
            eng.submit(prompt(8, 2))
        assert ei.value.reason == "draining"
    finally:
        eng.close()
    with pytest.raises(ServingShutdown):
        eng.submit(prompt(8, 2))


# ---------------------------------------------------------------------------
# bit-exact token parity: continuous vs single-request
# ---------------------------------------------------------------------------

def test_bit_exact_parity_continuous_vs_single(model):
    """THE correctness pin: a request decoded in a full continuous
    batch (joining/leaving neighbours, shared page pool) emits the
    BIT-identical token sequence it emits running alone — masked
    carries, the null page, and per-slot page tables make batch-mates
    invisible."""
    prompts = [prompt(10, 2), prompt(11, 7), prompt(12, 3),
               prompt(13, 5)]
    mns = [6, 3, 8, 4]
    eng = make_engine(model, ladder=(1, 2, 4), max_context=32)
    try:
        streams = [eng.submit(p, max_new=m)
                   for p, m in zip(prompts, mns)]
        drive(eng)
        batched = [s.result(0) for s in streams]
    finally:
        eng.close()
    single = []
    eng1 = make_engine(model, ladder=(1, 2, 4), max_context=32)
    try:
        for p, m in zip(prompts, mns):
            eng1._draining = False       # sequential: reopen after drain
            s = eng1.submit(p, max_new=m)
            assert eng1.drain()
            single.append(s.result(0))
    finally:
        eng1.close()
    assert batched == single


def test_run_decode_static_and_continuous_same_tokens(model):
    """The bench A/B's honesty condition: both policies run the same
    compiled programs over the same requests — total tokens identical,
    only the schedule differs."""
    prompts = [prompt(20 + i, 2 + (i % 4)) for i in range(6)]
    mns = [5, 2, 3, 2, 4, 2]
    cont = serving.run_decode(model, prompts, mns, ladder=(1, 2),
                              page_size=4, warmup=False)
    stat = serving.run_decode(model, prompts, mns, ladder=(1, 2),
                              page_size=4, static=True, warmup=False)
    assert cont["tokens"] == stat["tokens"] == sum(mns)
    assert cont["mode"] == "continuous" and stat["mode"] == "static"
    for rep in (cont, stat):
        assert rep["ttft_p50_ms"] is not None
        assert rep["tpot_p50_ms"] is not None
        assert rep["decode_tokens_per_sec"] > 0
        assert 0 < rep["kv_page_util"] <= 1.0


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------

def test_chunked_prefill_never_starves_decode(model, monkeypatch):
    """Strict alternation: while decode-ready slots exist, two prefill
    chunks never run back-to-back — a 12-token prompt (3 chunks at
    MXNET_DECODE_PREFILL_CHUNK=4) cannot stall the running batch."""
    monkeypatch.setenv("MXNET_DECODE_PREFILL_CHUNK", "4")
    eng = make_engine(model, ladder=(2,))
    kinds = []
    real_pre, real_dec = eng._dispatch_prefill, eng._dispatch_decode

    def spy_pre(slot):
        dec_ready = [s for s in range(eng.slots)
                     if eng._occupant[s] is not None
                     and eng._occupant[s].phase == "decode"]
        kinds.append(("prefill", bool(dec_ready)))
        return real_pre(slot)

    def spy_dec(slots):
        kinds.append(("decode", True))
        return real_dec(slots)

    eng._dispatch_prefill = spy_pre
    eng._dispatch_decode = spy_dec
    try:
        assert eng._chunk == 4
        s_short = eng.submit(prompt(30, 2), max_new=8)
        s_long = eng.submit(prompt(31, 12), max_new=2)
        drive(eng)
        assert len(s_short.result(0)) == 8
        assert len(s_long.result(0)) == 2
        assert eng.stats["prefill_chunks"] == 1 + 3   # short + 12/4
        for i in range(1, len(kinds)):
            if kinds[i][0] == "prefill" and kinds[i][1]:
                assert kinds[i - 1][0] != "prefill", \
                    "two consecutive prefill chunks starved the " \
                    "decode batch"
    finally:
        eng.close()


def test_prefill_chunk_count_and_positions(model, monkeypatch):
    monkeypatch.setenv("MXNET_DECODE_PREFILL_CHUNK", "4")
    eng = make_engine(model, ladder=(1,))
    try:
        s = eng.submit(prompt(32, 10), max_new=2)    # 10 -> 4+4+2
        drive(eng)
        assert eng.stats["prefill_chunks"] == 3
        assert len(s.result(0)) == 2
        assert eng.stats["steps"] == 1               # 1 decode step
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# admission: KV-page exhaustion
# ---------------------------------------------------------------------------

def test_kv_exhaustion_sheds_typed_overloaded(model):
    # pool sized for ONE request's worst case: the second is shed
    eng = make_engine(model, ladder=(1, 2), num_pages=4, depth=8)
    try:
        s = eng.submit(prompt(40, 3), max_new=6)     # needs 3 pages
        with pytest.raises(Overloaded) as ei:
            eng.submit(prompt(41, 3), max_new=6)
        assert ei.value.reason == "kvcache"
        assert eng.stats["rejected"] == 1
        drive(eng)
        assert len(s.result(0)) == 6                 # victim unharmed
        # pages released at retire: the pool admits again
        s2 = eng.submit(prompt(41, 3), max_new=6)
        drive(eng)
        assert len(s2.result(0)) == 6
    finally:
        eng.close()


def test_oversized_request_is_an_error_not_a_shed(model):
    eng = make_engine(model, max_context=8)
    try:
        with pytest.raises(MXNetError, match="max_context"):
            eng.submit(prompt(42, 6), max_new=6)     # 6+6+1 > 8
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# the guarded zero-sync streamed run
# ---------------------------------------------------------------------------

def test_streamed_run_zero_unblessed_syncs(model, monkeypatch):
    """12+ scheduler iterations under MXNET_TRANSFER_GUARD=raise: the
    retire is the ONE blessed sync; next-step tokens chain device-side,
    so the wait_to_read counter must not move."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    eng = make_engine(model, ladder=(1, 2))
    try:
        eng.warmup()
        before = telemetry.value(telemetry.names.HOST_SYNCS,
                                 "wait_to_read") or 0
        streams = [eng.submit(prompt(50 + i, 2 + i), max_new=5 + i)
                   for i in range(3)]
        iters = drive(eng)
        after = telemetry.value(telemetry.names.HOST_SYNCS,
                                "wait_to_read") or 0
        assert iters >= 12
        assert [len(s.result(0)) for s in streams] == [5, 6, 7]
        assert after - before == 0, \
            "decode hot loop performed an unblessed NDArray host sync"
    finally:
        eng.close()


def test_warmup_means_zero_live_traces(model):
    eng = make_engine(model, ladder=(1, 2))
    try:
        exes = eng.warmup()
        assert set(exes) == {("decode", 1), ("decode", 2),
                             ("prefill", 1), ("prefill", 2)}
        assert eng.n_traces == 0
        streams = [eng.submit(prompt(60 + i, 3), max_new=3)
                   for i in range(2)]
        drive(eng)
        for s in streams:
            assert len(s.result(0)) == 3
        assert eng.n_traces == 0, "AOT executables must serve traffic"
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# static analysis + telemetry
# ---------------------------------------------------------------------------

def test_decode_program_analysis(model):
    eng = make_engine(model)
    try:
        report = eng.analyze()
        assert report.mode == "predict"
        assert report.ok, report.summary()
        assert not report.collectives.ops
        assert report.host_transfers == []
    finally:
        eng.close()


def test_decode_metrics_flow(model):
    reg = telemetry.registry()
    tok0 = reg.value(telemetry.names.DECODE_TOKENS) or 0
    ttft = reg.get(telemetry.names.DECODE_TTFT_SECONDS)
    tpot = reg.get(telemetry.names.DECODE_TPOT_SECONDS)
    ttft0, tpot0 = ttft.count(), tpot.count()
    eng = make_engine(model, ladder=(1, 2))
    try:
        streams = [eng.submit(prompt(70 + i, 2), max_new=3)
                   for i in range(2)]
        drive(eng)
        for s in streams:
            s.result(0)
    finally:
        eng.close()
    assert (reg.value(telemetry.names.DECODE_TOKENS) or 0) - tok0 == 6
    assert ttft.count() - ttft0 == 2     # one first token per request
    assert tpot.count() - tpot0 == 4     # the rest are inter-token gaps
    assert (reg.value(telemetry.names.DECODE_ACTIVE_SLOTS) or 0) == 0
    used = reg.value(telemetry.names.DECODE_KV_PAGES, "used") or 0
    assert used == 0                     # everything released


# ---------------------------------------------------------------------------
# loadgen streaming aggregation
# ---------------------------------------------------------------------------

def test_streaming_summary_percentiles():
    from mxnet_tpu.serving import loadgen
    recs = [{"tokens": 3, "ttft_s": 0.010, "tpot_s": [0.002, 0.002]},
            {"tokens": 2, "ttft_s": 0.030, "tpot_s": [0.004]},
            {"tokens": 0, "ttft_s": None, "tpot_s": []}]
    out = loadgen.streaming_summary(recs, wall=0.5)
    assert out["stream_tokens"] == 5
    assert out["tokens_per_sec"] == pytest.approx(10.0)
    assert out["ttft_p50_ms"] == pytest.approx(20.0)
    assert out["tpot_p50_ms"] == pytest.approx(2.0)
    assert out["ttft_p99_ms"] <= 30.0 + 1e-6


def test_closed_loop_attaches_streaming_stats(model):
    """An issue() that returns DecodeStream.record() gets TTFT/TPOT
    percentiles and tokens_per_sec next to the request-level report."""
    from mxnet_tpu.serving import loadgen
    eng = make_engine(model, ladder=(1, 2), depth=16, start=True)
    try:
        eng.warmup()

        def issue(i):
            s = eng.submit(prompt(80 + i, 2), max_new=3)
            s.result(30.0)
            return s.record()

        rep = loadgen.run_closed_loop(issue, concurrency=2, requests=6)
    finally:
        eng.close()
    assert rep["outcomes"]["ok"] == 6
    assert rep["stream_tokens"] == 18
    assert rep["ttft_p50_ms"] is not None
    assert rep["tpot_p50_ms"] is not None
    assert rep["tokens_per_sec"] > 0


# ---------------------------------------------------------------------------
# the single-step decode kernel
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh", "rnn_relu"])
def test_rnn_decode_step_interpret_matches_xla(mode, monkeypatch):
    from mxnet_tpu.ops.kernels import rnn_scan as K
    rng = onp.random.RandomState(3)
    S, H = 4, 8
    G = {"lstm": 4, "gru": 3}.get(mode, 1)
    xw = jnp.asarray(rng.randn(S, G * H).astype("float32"))
    h = jnp.asarray(rng.randn(S, H).astype("float32"))
    c = jnp.asarray(rng.randn(S, H).astype("float32"))
    w_hh = jnp.asarray((rng.randn(G * H, H) * 0.3).astype("float32"))
    b_hh = jnp.asarray(rng.randn(G * H).astype("float32"))
    monkeypatch.setenv("MXNET_PALLAS", "off")
    h_x, c_x = K.rnn_decode_step(xw, h, c, w_hh, b_hh, mode)
    monkeypatch.setenv("MXNET_PALLAS", "on")   # interpret on CPU
    h_i, c_i = K.rnn_decode_step(xw, h, c, w_hh, b_hh, mode)
    onp.testing.assert_allclose(onp.asarray(h_x), onp.asarray(h_i),
                                atol=1e-6)
    if mode == "lstm":
        onp.testing.assert_allclose(onp.asarray(c_x), onp.asarray(c_i),
                                    atol=1e-6)
    else:
        assert c_x is None and c_i is None


@pytest.mark.parametrize("mode", ["lstm", "gru"])
def test_decode_step_matches_scan_position(mode, monkeypatch):
    """A token decoded step-by-step is bit-identical to the same
    position inside a full rnn_scan (the decode kernel's correctness
    anchor)."""
    from mxnet_tpu.ops import rnn as rnn_ops
    from mxnet_tpu.ops.kernels import rnn_scan as K
    monkeypatch.setenv("MXNET_PALLAS", "off")
    rng = onp.random.RandomState(5)
    T, N, H = 5, 3, 8
    G = {"lstm": 4, "gru": 3}[mode]
    xw = jnp.asarray(rng.randn(T, N, G * H).astype("float32"))
    h = jnp.zeros((N, H), "float32")
    c = jnp.zeros((N, H), "float32") if mode == "lstm" else None
    w_hh = jnp.asarray((rng.randn(G * H, H) * 0.3).astype("float32"))
    b_hh = jnp.asarray(rng.randn(G * H).astype("float32"))
    ys, h_T, _ = rnn_ops.scan_reference(xw, h, c, w_hh, b_hh, mode)
    for t in range(T):
        h, c = K.rnn_decode_step(xw[t], h, c, w_hh, b_hh, mode)
        onp.testing.assert_allclose(onp.asarray(ys[t]), onp.asarray(h),
                                    atol=1e-6)
    onp.testing.assert_allclose(onp.asarray(h_T), onp.asarray(h),
                                atol=1e-6)


def test_paged_attention_reads_through_page_table():
    """paged_decode_attention over a scattered page layout equals dense
    attention over the gathered history."""
    from mxnet_tpu.ops.attention import paged_decode_attention
    rng = onp.random.RandomState(9)
    S, nH, hd, P, ps = 2, 2, 8, 6, 4
    q = jnp.asarray(rng.randn(S, nH, hd).astype("float32"))
    k_pages = jnp.asarray(rng.randn(P, ps, nH, hd).astype("float32"))
    v_pages = jnp.asarray(rng.randn(P, ps, nH, hd).astype("float32"))
    table = jnp.asarray(onp.array([[3, 1, 0], [5, 2, 4]], onp.int32))
    lengths = jnp.asarray(onp.array([5, 7], onp.int32))
    out = onp.asarray(paged_decode_attention(q, k_pages, v_pages,
                                             table, lengths))
    scale = 1.0 / onp.sqrt(hd)
    for s in range(S):
        hist_k = onp.concatenate(
            [onp.asarray(k_pages[int(p)]) for p in table[s]])
        hist_v = onp.concatenate(
            [onp.asarray(v_pages[int(p)]) for p in table[s]])
        L = int(lengths[s])
        for head in range(nH):
            logits = hist_k[:L, head] @ onp.asarray(q[s, head]) * scale
            w = onp.exp(logits - logits.max())
            w /= w.sum()
            ref = w @ hist_v[:L, head]
            onp.testing.assert_allclose(out[s, head], ref, atol=1e-5)
