"""Ops-facing tools: parse_log, rec2idx, bandwidth/measure, diagnose,
and launch.py's kill-hygiene protocol.

Reference analogs: tools/parse_log.py, tools/rec2idx.py,
tools/bandwidth/measure.py, tools/diagnose.py; the graceful-stop
protocol is this framework's own (VERDICT r3 weak #6: a hard kill of a
TPU-owning process can wedge a tunneled relay for hours).
"""
import importlib.util
import os
import signal
import subprocess
import sys
import time

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# parse_log
# ---------------------------------------------------------------------------

def test_parse_log_reference_grammar(tmp_path, capsys):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Train-accuracy=0.70\n"
        "INFO Epoch[0] Validation-accuracy=0.65\n"
        "INFO Epoch[0] Time cost=12.5\n"
        "INFO Epoch[1] Train-accuracy=0.80\n"
        "INFO Epoch[1] Validation-accuracy=0.75\n"
        "INFO Epoch[1] Time cost=11.0\n")
    parse_log = _load("tools/parse_log.py", "parse_log")
    parse_log.main([str(log)])
    out = capsys.readouterr().out
    assert "| epoch |" in out and "train-accuracy" in out
    assert "0.700000" in out and "0.750000" in out and "11.0" in out

    parse_log.main([str(log), "--format", "none"])
    out = capsys.readouterr().out
    assert out.startswith("epoch\t")


def test_parse_log_estimator_grammar(tmp_path, capsys):
    log = tmp_path / "est.log"
    log.write_text("[Epoch 0] train accuracy: 0.5\n"
                   "[Epoch 0] validation accuracy: 0.4\n"
                   "[Epoch 0] time used: 3.2\n")
    parse_log = _load("tools/parse_log.py", "parse_log2")
    parse_log.main([str(log)])
    out = capsys.readouterr().out
    assert "0.500000" in out and "0.400000" in out


# ---------------------------------------------------------------------------
# rec2idx
# ---------------------------------------------------------------------------

def test_rec2idx_roundtrip(tmp_path, capsys):
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    payloads = [bytes([i]) * (10 + i) for i in range(5)]
    w = recordio.MXRecordIO(rec_path, "w")
    for p in payloads:
        w.write(p)
    w.close()

    rec2idx = _load("tools/rec2idx.py", "rec2idx")
    assert rec2idx.main([rec_path, idx_path]) == 0
    assert "indexed 5 records" in capsys.readouterr().out

    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert len(r.keys) == 5
    for i, p in enumerate(payloads):
        assert r.read_idx(i) == p
    assert r.read_idx(3) == payloads[3]  # random access after seek
    r.close()


# ---------------------------------------------------------------------------
# bandwidth / diagnose
# ---------------------------------------------------------------------------

def test_bandwidth_measure_local():
    measure = _load("tools/bandwidth/measure.py", "bw_measure")
    args = measure.parse_args(["--network", "resnet18_v1",
                               "--kv-store", "local",
                               "--num-batches", "2",
                               "--num-classes", "10"])
    result = measure.run(args)
    assert result["gbps"] > 0
    assert result["params_mb"] > 10  # resnet18 is ~45 MB of params


def test_bandwidth_measure_detects_corruption(monkeypatch):
    measure = _load("tools/bandwidth/measure.py", "bw_measure2")
    assert measure.error([], []) == 0


def test_diagnose_smoke(capsys):
    diagnose = _load("tools/diagnose.py", "diagnose")
    assert diagnose.main([]) == 0
    out = capsys.readouterr().out
    for section in ("Python Info", "MXNet(TPU) Info", "Accelerator Info",
                    "Environment"):
        assert section in out
    assert "Network Test" not in out  # egress checks are opt-in
    assert "Program Analysis" not in out  # analysis section is opt-in


def test_diagnose_analysis_section(capsys):
    """--analysis: env reports include compiled-program health — the
    tiny-MLP fused step's ProgramReport with an OK verdict."""
    diagnose = _load("tools/diagnose.py", "diagnose2")
    assert diagnose.main(["--analysis"]) == 0
    out = capsys.readouterr().out
    assert "Program Analysis" in out
    assert "ProgramReport(mode=fused" in out
    assert "verdict      : OK" in out


def test_diagnose_fusion_section(capsys):
    """--fusion: the census prints a kernel table for both canonical
    legs (tiny MLP + the LSTM-LM example architecture) with bound
    classes and the stranded-op verdict."""
    diagnose = _load("tools/diagnose.py", "diagnose4")
    assert diagnose.main(["--fusion"]) == 0
    out = capsys.readouterr().out
    assert "Fusion Census" in out
    assert "tiny MLP" in out and "LSTM LM" in out
    assert "fusions=" in out and "boundary_bytes=" in out
    assert "memory" in out            # bound class column populated
    assert "stranded ops : none above the" in out


def test_diagnose_sharding_section(capsys):
    """--sharding: the zero-sharded MLP's sharding-flow table (buffers
    with resolved layouts), the implicit-reshard verdict, and the
    per-axis communication cost table."""
    diagnose = _load("tools/diagnose.py", "diagnose_sh")
    assert diagnose.main(["--sharding"]) == 0
    out = capsys.readouterr().out
    assert "Sharding Analysis" in out
    assert "pack=zero-dp" in out
    assert "P(dp)" in out                       # resolved state shard
    assert "implicit reshards: none above the" in out
    assert "axis 'dp':" in out                  # per-axis cost line
    assert "table digest:" in out


def test_diagnose_kernels_section(capsys):
    """--kernels: the per-kernel dispatch table (path + reason for
    every kernel the gate knows) and the interpret-vs-xla parity
    probes, bit-exact on this backend."""
    diagnose = _load("tools/diagnose.py", "diagnose5")
    assert diagnose.main(["--kernels"]) == 0
    out = capsys.readouterr().out
    assert "Pallas Kernel Layer" in out
    assert "MXNET_PALLAS=" in out
    for name in ("rnn_scan", "opt_update", "layernorm", "bias_gelu",
                 "flash_attention"):
        assert name in out
    assert out.count("bit-exact") == 2


def test_diagnose_autotune_section(capsys):
    """--autotune: the registered tunable table (every knob with its
    default, grid and consumer seam), then the 3-trial analytical
    sweep on the tiny MLP shown twice against a scratch DB — first run
    a cache MISS that searches, second run a HIT that replays with
    zero trials."""
    from mxnet_tpu.tuning import space
    before = space.overrides()
    diagnose = _load("tools/diagnose.py", "diagnose_at")
    assert diagnose.main(["--autotune"]) == 0
    out = capsys.readouterr().out
    assert "Self-Tuning Autopilot" in out
    assert "MXNET_AUTOTUNE=" in out
    for name in ("engine.inflight_steps", "kernels.vmem_tile_budget",
                 "kernels.rnn_block_t", "zero.shard_min_size",
                 "serving.max_batch", "serving.batch_timeout_ms"):
        assert name in out
    assert "-> engine.inflight_steps() -> DispatchWindow" in out
    assert "cache MISS -> searched + persisted  trials=3" in out
    assert "cache HIT (replayed, 0 trials)  trials=0" in out
    assert "winning config:" in out
    # the section restores the process overrides it found
    assert space.overrides() == before


def test_diagnose_numerics_section(capsys, tmp_path, monkeypatch):
    """--numerics: the 10-step norm table prints with finite values and
    the simulated-divergence demo produces exactly one anomaly plus a
    post-mortem dump in MXNET_NUMERICS_DUMP_DIR."""
    from mxnet_tpu import telemetry
    telemetry.reset()
    monkeypatch.setenv("MXNET_NUMERICS_DUMP_DIR", str(tmp_path))
    diagnose = _load("tools/diagnose.py", "diagnose3")
    assert diagnose.main(["--numerics"]) == 0
    out = capsys.readouterr().out
    assert "Training Numerics" in out
    assert "grad_norm" in out and "upd/w ratio" in out
    assert "anomalies    : 1 (want exactly 1)" in out
    assert list(tmp_path.glob("mx_numerics_*.json"))
    telemetry.reset()


def test_diagnose_serving_section(capsys):
    """--serving: AOT-compiles the tiny bucketed predictor, runs a
    concurrent closed-loop burst through the dynamic batcher, and
    prints the stats table plus the p50/p99 latency probe — then the
    resilience panel: one injected revocation under a burst with
    breaker transitions, recovery downtime, and the outcome census."""
    diagnose = _load("tools/diagnose.py", "diagnose7")
    assert diagnose.main(["--serving"]) == 0
    out = capsys.readouterr().out
    assert "Inference Serving" in out
    assert "4 programs" in out            # one per shape bucket
    assert "throughput   :" in out and "req/s" in out
    assert "latency      : p50" in out and "p99" in out
    assert "batch fill" in out
    assert "errors        0" in out
    assert "compile cache:" in out
    # resilience panel: exactly one recovery, breaker round trip
    assert "resilience (1 injected revocation under burst)" in out
    assert "recoveries   : 1" in out
    assert "closed -> open -> half_open -> closed" in out
    assert "outcomes     :" in out
    assert "shed policy  : MXNET_SERVING_SHED=" in out


def test_diagnose_decode_section(capsys):
    """--decode: AOT-compiles the continuous-batching decode engine
    over its slot ladder, runs a 6-request streamed burst, and prints
    the mid-burst slot table, the page-allocator census, the TTFT/TPOT
    probe and the decode-kernel dispatch decision."""
    diagnose = _load("tools/diagnose.py", "diagnose_dec")
    assert diagnose.main(["--decode"]) == 0
    out = capsys.readouterr().out
    assert "Continuous-Batching Decode" in out
    assert "slot ladder" in out and "prefill chunk" in out
    assert "-- slot table (mid-burst) --" in out
    assert "-- page allocator --" in out
    assert "used_pages" in out and "bytes_per_page" in out
    assert "-- streamed burst --" in out
    assert "ttft" in out and "tpot" in out and "tok/s" in out
    assert "decode kernel:" in out and "MXNET_PALLAS=" in out
    # speculative panel: drafter line, acceptance histogram, shared/COW
    # page census
    assert "-- speculative decode --" in out
    assert "MXNET_DECODE_SPEC_K" in out
    assert "drafter      : NgramDrafter" in out
    assert "verify steps :" in out and "accept" in out
    assert "prefix cache :" in out and "COW copies" in out
    assert "decode check failed" not in out


def test_diagnose_elastic_section(capsys):
    """--elastic: runs a tiny supervised TrainLoop, injects one mid-run
    fault, and prints the RecoveryLog table (exactly one recovery) and
    the restore provenance."""
    from mxnet_tpu.testing import faults
    diagnose = _load("tools/diagnose.py", "diagnose6")
    try:
        assert diagnose.main(["--elastic"]) == 0
    finally:
        faults.reset()
    out = capsys.readouterr().out
    assert "Elastic Supervisor" in out
    assert "1 recovery(ies)" in out
    assert "provenance   : restored step" in out
    assert "-- recovery log --" in out
    assert ("device_lost" in out) or ("transient" in out)


def test_diagnose_threads_section(capsys):
    """--threads: prints the audited-lock table, the observed
    lock-order graph's cycle status, a planted two-lock inversion demo
    (on a private graph — the global hierarchy stays clean), and a
    contention snapshot with a live waiter."""
    from mxnet_tpu.analysis import threads
    diagnose = _load("tools/diagnose.py", "diagnose_thr")
    assert diagnose.main(["--threads"]) == 0
    out = capsys.readouterr().out
    assert "Concurrency Audit" in out
    assert "MXNET_LOCK_STALL_SEC=" in out
    assert "-- audited locks" in out
    assert "order graph" in out
    assert "-- planted inversion demo (1 finding) --" in out
    assert "demo.inversion.a" in out and "demo.inversion.b" in out
    assert "-- contention snapshot --" in out
    assert "demo.contention" in out and "1 waiter(s)" in out
    # the demo's inversion must NOT have leaked into the global graph
    assert not any("demo.inversion" in f"{a}{b}"
                   for a, b in threads.graph().edge_pairs())


def test_diagnose_overlap_section(capsys):
    """--overlap: compiles the zero-sharded adam MLP serial AND
    bucketed on the virtual dp mesh and prints each schedule's
    exposed-communication table (docs/PERF_NOTES.md \"Communication
    overlap\")."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("overlap section needs a >=2-device mesh")
    diagnose = _load("tools/diagnose.py", "diagnose7")
    assert diagnose.main(["--overlap"]) == 0
    out = capsys.readouterr().out
    assert "Communication Overlap" in out
    assert "serial (bucket_bytes=0)" in out
    assert "bucketed (bucket_bytes=16384)" in out
    assert "exposed=" in out and "collective" in out
    assert "overlap check failed" not in out


# ---------------------------------------------------------------------------
# launch.py graceful stop
# ---------------------------------------------------------------------------

def _spawn(code):
    """Start a child and block until its signal handlers are installed
    (it prints 'ready')."""
    p = subprocess.Popen([sys.executable, "-c", code],
                         stdout=subprocess.PIPE)
    assert p.stdout.readline().strip() == b"ready"
    return p


_READY = "import sys; print('ready'); sys.stdout.flush()\n"


def test_graceful_stop_grace_then_kill():
    launch = _load("tools/launch.py", "launch_mod")
    # p1 exits promptly on SIGTERM; p2 ignores SIGTERM (CPU-pinned ->
    # may be hard-killed after the grace window)
    p1 = _spawn("import signal,time\n"
                "signal.signal(signal.SIGTERM, lambda *a: exit(0))\n"
                + _READY + "time.sleep(60)")
    p2 = _spawn("import signal,time\n"
                "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
                + _READY + "time.sleep(60)")
    t0 = time.time()
    launch._graceful_stop([p1, p2], [False, False], grace=1.0)
    p1.wait(timeout=5)
    p2.wait(timeout=5)
    assert time.time() - t0 < 10
    assert p1.returncode == 0          # exited via its SIGTERM handler
    assert p2.returncode == -signal.SIGKILL  # escalated


def test_graceful_stop_never_hard_kills_accel_owner():
    launch = _load("tools/launch.py", "launch_mod2")
    p = _spawn("import signal,time\n"
               "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
               + _READY + "time.sleep(60)")
    try:
        launch._graceful_stop([p], [True], grace=1.0)
        time.sleep(0.5)
        assert p.poll() is None, \
            "accelerator-owning process must not be SIGKILLed"
    finally:
        p.kill()
        p.wait(timeout=5)


def test_may_own_accelerator():
    launch = _load("tools/launch.py", "launch_mod3")
    assert launch._may_own_accelerator({}) is True
    assert launch._may_own_accelerator({"JAX_PLATFORMS": "cpu"}) is False
    assert launch._may_own_accelerator({"JAX_PLATFORMS": "tpu"}) is True
