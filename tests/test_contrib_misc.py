"""Misc contrib ops (reference src/operator/contrib/): quadratic,
gradient multiplier, allclose, index_copy/index_array, boolean_mask,
arange_like, graph (dgl) CSR ops, hawkes_ll — plus the np gap-fill
(bartlett/trim_zeros/apply_along_axis/polyval/tril_indices/
fill_diagonal)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.base import MXNetError


def test_quadratic_and_gradientmultiplier():
    x = nd.array([1.0, 2.0, 3.0])
    out = nd.contrib.quadratic(x, a=1.0, b=2.0, c=3.0)
    onp.testing.assert_allclose(out.asnumpy(), [6.0, 11.0, 18.0])

    g = nd.array([1.0, 2.0])
    g.attach_grad()
    with autograd.record():
        y = nd.contrib.gradientmultiplier(g, scalar=-0.5)  # grad reversal
        loss = (y * nd.array([3.0, 4.0])).sum()
    loss.backward()
    onp.testing.assert_allclose(y.asnumpy(), g.asnumpy())  # identity fwd
    onp.testing.assert_allclose(g.grad.asnumpy(), [-1.5, -2.0])


def test_allclose_op():
    a = nd.array([1.0, 2.0])
    assert float(nd.contrib.allclose(a, nd.array([1.0, 2.0 + 1e-7]))
                 .asnumpy()) == 1.0
    assert float(nd.contrib.allclose(a, nd.array([1.0, 2.1])).asnumpy()) \
        == 0.0


def test_index_copy_and_index_array():
    old = nd.zeros((4, 2))
    out = nd.contrib.index_copy(old, nd.array([1, 3]),
                                nd.array([[1.0, 1.0], [2.0, 2.0]]))
    onp.testing.assert_allclose(out.asnumpy(),
                                [[0, 0], [1, 1], [0, 0], [2, 2]])
    idx = nd.contrib.index_array(nd.ones((3, 2))).asnumpy()
    assert idx.shape == (3, 2, 2)
    onp.testing.assert_array_equal(idx[2, 1], [2, 1])
    idx2 = nd.contrib.index_array(nd.ones((3, 2, 2)), axes=(1, 0)).asnumpy()
    onp.testing.assert_array_equal(idx2[1, 0, 1], [0, 1])


def test_boolean_mask_and_arange_like():
    data = nd.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    out = nd.contrib.boolean_mask(data, nd.array([1, 0, 1]))
    onp.testing.assert_allclose(out.asnumpy(), [[1, 2], [5, 6]])
    al = nd.contrib.arange_like(nd.ones((2, 3))).asnumpy()
    onp.testing.assert_allclose(al, [[0, 1, 2], [3, 4, 5]])
    al2 = nd.contrib.arange_like(nd.ones((2, 3)), start=10, step=2,
                                 axis=1).asnumpy()
    onp.testing.assert_allclose(al2, [10, 12, 14])


def _toy_graph():
    # 4 vertices; edges with ids as data
    dense = onp.array([[0, 1, 0, 2],
                       [3, 0, 4, 0],
                       [0, 5, 0, 0],
                       [6, 0, 0, 0]], "float32")
    return nd.sparse.csr_matrix(dense)


def test_graph_ops():
    g = _toy_graph()
    assert int(nd.contrib.getnnz(g).asnumpy()) == 6
    onp.testing.assert_array_equal(
        nd.contrib.getnnz(g, axis=1).asnumpy(), [2, 2, 1, 1])
    eid = nd.contrib.edge_id(g, nd.array([0, 1, 2]),
                             nd.array([3, 0, 0])).asnumpy()
    onp.testing.assert_allclose(eid, [2.0, 3.0, -1.0])
    adj = nd.contrib.dgl_adjacency(g)
    onp.testing.assert_allclose(adj.asnumpy(),
                                (onp.asarray(g.asnumpy()) != 0)
                                .astype("float32"))

    ids, sub = nd.contrib.dgl_csr_neighbor_uniform_sample(
        g, nd.array([0]), num_hops=1, num_neighbor=1,
        max_num_vertices=6, seed=0)
    ids = ids.asnumpy()
    count = int(ids[-1])
    assert count >= 2 and int(ids[0]) == 0
    # sampled edges are a subset of the original graph
    sd = sub.asnumpy()
    orig = g.asnumpy()
    mask = sd != 0
    onp.testing.assert_allclose(sd[mask], orig[mask])
    assert mask.sum() == 1  # one neighbor sampled from one seed


def _hawkes_ref(lda, alpha, beta, state, lags, marks, vl, mt):
    """Direct per-sample loop over the closed-form exp-kernel Hawkes
    log likelihood."""
    n, k = lda.shape
    ll = onp.zeros(n)
    s_T = onp.zeros((n, k))
    for i in range(n):
        times = onp.cumsum(lags[i][:vl[i]])
        ms = marks[i][:vl[i]]
        acc = 0.0
        for j in range(vl[i]):
            t_j = times[j]
            lam = lda[i].copy()
            for kk in range(k):
                mem = state[i, kk] * onp.exp(-beta[kk] * t_j)
                prior = [t for t, m in zip(times[:j], ms[:j]) if m == kk]
                mem += sum(onp.exp(-beta[kk] * (t_j - t)) for t in prior)
                lam[kk] += alpha[kk] * beta[kk] * mem
            acc += onp.log(lam[ms[j]])
        comp = 0.0
        for kk in range(k):
            pts = [t for t, m in zip(times, ms) if m == kk]
            comp += lda[i, kk] * mt[i]
            comp += alpha[kk] * sum(1 - onp.exp(-beta[kk] * (mt[i] - t))
                                    for t in pts)
            comp += alpha[kk] * state[i, kk] * \
                (1 - onp.exp(-beta[kk] * mt[i]))
            s_T[i, kk] = state[i, kk] * onp.exp(-beta[kk] * mt[i]) + \
                sum(onp.exp(-beta[kk] * (mt[i] - t)) for t in pts)
        ll[i] = acc - comp
    return ll, s_T


def test_hawkes_ll_matches_direct_computation():
    rng = onp.random.RandomState(0)
    n, k, t = 3, 2, 5
    lda = rng.uniform(0.5, 1.5, (n, k)).astype("float32")
    alpha = rng.uniform(0.2, 0.6, (k,)).astype("float32")
    beta = rng.uniform(0.5, 2.0, (k,)).astype("float32")
    state = rng.uniform(0, 1, (n, k)).astype("float32")
    lags = rng.uniform(0.1, 0.5, (n, t)).astype("float32")
    marks = rng.randint(0, k, (n, t)).astype("int32")
    vl = onp.array([5, 3, 4], "int32")
    mt = onp.array([4.0, 3.0, 3.5], "float32")

    ll, s_end = nd.contrib.hawkes_ll(
        nd.array(lda), nd.array(alpha), nd.array(beta), nd.array(state),
        nd.array(lags), nd.array(marks), nd.array(vl), nd.array(mt))
    ref_ll, ref_s = _hawkes_ref(lda, alpha, beta, state, lags, marks, vl, mt)
    onp.testing.assert_allclose(ll.asnumpy(), ref_ll, rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(s_end.asnumpy(), ref_s, rtol=1e-4,
                                atol=1e-4)


def test_hawkes_ll_differentiable():
    n, k, t = 2, 2, 3
    lda = nd.array(onp.full((n, k), 1.0, "float32"))
    lda.attach_grad()
    args = [nd.array(onp.full((k,), 0.5, "float32")),
            nd.array(onp.full((k,), 1.0, "float32")),
            nd.array(onp.zeros((n, k), "float32")),
            nd.array(onp.full((n, t), 0.3, "float32")),
            nd.array(onp.zeros((n, t), "int32")),
            nd.array(onp.full((n,), t, "int32")),
            nd.array(onp.full((n,), 2.0, "float32"))]
    with autograd.record():
        ll, _ = nd.contrib.hawkes_ll(lda, *args)
        loss = ll.sum()
    loss.backward()
    g = lda.grad.asnumpy()
    assert onp.isfinite(g).all() and (g != 0).any()


def test_np_gap_fill_functions():
    """bartlett/trim_zeros/apply_along_axis/polyval/tril_indices/
    fill_diagonal/diag_indices_from (reference src/operator/numpy/
    np_window_op.cc et al.)."""
    onp.testing.assert_allclose(mx.np.bartlett(5).asnumpy(),
                                onp.bartlett(5), rtol=1e-6)
    onp.testing.assert_allclose(
        mx.np.trim_zeros(mx.np.array([0, 0, 1, 2, 0])).asnumpy(), [1, 2])
    x = mx.np.array(onp.arange(12.0).reshape(3, 4))
    onp.testing.assert_allclose(
        mx.np.apply_along_axis(lambda r: r.sum(), 1, x).asnumpy(),
        onp.arange(12.0).reshape(3, 4).sum(1))
    onp.testing.assert_allclose(
        mx.np.polyval(mx.np.array([1.0, 0.0, -1.0]),
                      mx.np.array([2.0, 3.0])).asnumpy(), [3.0, 8.0])
    r, c = mx.np.tril_indices(3, k=-1)
    onp.testing.assert_array_equal(r.asnumpy(), [1, 2, 2])
    onp.testing.assert_array_equal(c.asnumpy(), [0, 0, 1])
    a = mx.np.array(onp.zeros((3, 3), "float32"))
    mx.np.fill_diagonal(a, 7.0)
    onp.testing.assert_allclose(onp.diagonal(a.asnumpy()), [7, 7, 7])
    rr, cc = mx.np.diag_indices_from(a)
    onp.testing.assert_array_equal(rr.asnumpy(), [0, 1, 2])


def test_review_fix_semantics():
    """apply_along_axis multi-dim placement, arange_like repeat+axis,
    adjacency with explicit zero edges, seed-bounded sampling."""
    got = mx.np.apply_along_axis(
        lambda r: mx.np.array(onp.zeros((4, 5), "float32")), 0,
        mx.np.array(onp.ones((2, 3), "float32"))).shape
    want = onp.apply_along_axis(lambda r: onp.zeros((4, 5)), 0,
                                onp.ones((2, 3))).shape
    assert got == want
    onp.testing.assert_allclose(
        nd.contrib.arange_like(nd.ones((2, 4)), repeat=2, axis=1).asnumpy(),
        [0, 0, 1, 1])
    # explicitly-stored zero edge is still an edge in the adjacency
    g = nd.sparse.csr_matrix((onp.array([0.0, 7.0], "float32"),
                              onp.array([1, 0], "int32"),
                              onp.array([0, 1, 2], "int32")), shape=(2, 2))
    onp.testing.assert_allclose(nd.contrib.dgl_adjacency(g).asnumpy(),
                                [[0, 1], [1, 0]])
    # oversized seed set is bounded, count slot intact
    big = _toy_graph()
    ids, _ = nd.contrib.dgl_csr_neighbor_uniform_sample(
        big, nd.array([0, 1, 2, 3]), num_hops=1, num_neighbor=1,
        max_num_vertices=3, seed=0)
    ids = ids.asnumpy()
    assert int(ids[-1]) <= 2 and ids.shape == (3,)


def test_moe_aux_counts_pre_drop_routing():
    """Aux loss must keep penalizing imbalance past capacity saturation
    (Switch/GShard pre-drop fractions)."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.moe import moe_ffn
    rng = onp.random.RandomState(0)
    x = jnp.asarray(onp.abs(rng.randn(64, 8)).astype("float32"))
    gate = jnp.zeros((8, 4), "float32").at[:, 0].set(5.0)
    w1 = jnp.asarray(rng.randn(4, 8, 4).astype("float32"))
    w2 = jnp.asarray(rng.randn(4, 4, 8).astype("float32"))
    _, aux = moe_ffn(x, gate, w1, w2, top_k=1, capacity_factor=0.25)
    assert float(aux) > 3.5  # ~E at full imbalance, undamped by drops


def test_adamw_update_op_matches_manual():
    """reference contrib adamw_update (src/operator/contrib/adamw.cc):
    decoupled wd — w -= eta*(lr*m/(sqrt(v)+eps) + wd*w)."""
    w0 = onp.ones((4,), "float32")
    g0 = onp.full((4,), 0.5, "float32")
    w = nd.array(w0); g = nd.array(g0)
    m = nd.zeros((4,)); v = nd.zeros((4,))
    out = nd.contrib.adamw_update(w, g, m, v, rescale_grad=2.0, lr=0.1,
                                  eta=1.0, wd=0.01)
    gr = g0 * 2.0
    m_ref = 0.1 * gr
    v_ref = 0.001 * gr * gr
    upd = 0.1 * m_ref / (onp.sqrt(v_ref) + 1e-8) + 0.01 * w0
    onp.testing.assert_allclose(out.asnumpy(), w0 - upd, rtol=1e-5)
    onp.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-6)
    onp.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-6)
    assert out is w  # in-place semantics on the weight handle

    # multi-tensor variant walks every param
    ws = [nd.array(w0), nd.array(w0 * 2)]
    gs = [nd.array(g0), nd.array(g0)]
    ms = [nd.zeros((4,)), nd.zeros((4,))]
    vs = [nd.zeros((4,)), nd.zeros((4,))]
    outs = nd.contrib.multi_adamw_update(ws, gs, ms, vs, 1.0,
                                         lrs=[0.1, 0.2], wds=[0.0, 0.0],
                                         etas=[1.0, 1.0])
    assert len(outs) == 2 and (outs[1].asnumpy() != w0 * 2).any()

    # mixed precision: bf16 weight follows the fp32 master
    import jax.numpy as jnp
    wlow = nd.array(onp.ones((4,), "float32")).astype("bfloat16")
    w32 = nd.array(onp.ones((4,), "float32"))
    m2, v2 = nd.zeros((4,)), nd.zeros((4,))
    o = nd.contrib.mp_adamw_update(wlow, nd.array(g0), m2, v2, w32, 1.0,
                                   lr=0.1, eta=1.0)
    assert str(o._data.dtype) == "bfloat16"
    onp.testing.assert_allclose(onp.asarray(o._data, "float32"),
                                w32.asnumpy(), rtol=1e-2)


def test_adamw_optimizer_decoupled_decay():
    """AdamW wd must NOT flow through the moments (vs Adam's coupled wd)."""
    from mxnet_tpu import optimizer as opt
    w0 = onp.full((3,), 2.0, "float32")
    g = nd.array(onp.zeros((3,), "float32"))  # zero grad isolates wd
    aw = opt.create("adamw", learning_rate=0.1, wd=0.1)
    w = nd.array(w0)
    state = aw.create_state(0, w)
    aw.update(0, w, g, state)
    # zero grad: moments stay 0, update = lr * wd * w
    onp.testing.assert_allclose(w.asnumpy(), w0 - 0.1 * 0.1 * w0, rtol=1e-5)
    for s in state:
        onp.testing.assert_allclose(s.asnumpy(), onp.zeros(3))


def test_rand_zipfian_distribution_and_counts():
    s, et, es = nd.contrib.rand_zipfian(nd.array([0, 3]), 2000, 50)
    sv = s.asnumpy()
    assert sv.shape == (2000,) and (sv >= 0).all() and (sv < 50).all()
    # log-uniform: class 0 much more likely than class 40
    assert (sv == 0).sum() > (sv == 40).sum()
    # expected counts follow P(k) = log((k+2)/(k+1)) / log(range+1)
    p0 = onp.log(2.0) / onp.log(51.0)
    onp.testing.assert_allclose(et.asnumpy()[0], p0 * 2000, rtol=1e-4)
    # empirical frequency of class 0 within 3 sigma of expectation
    exp0 = p0 * 2000
    assert abs((sv == 0).sum() - exp0) < 4 * onp.sqrt(exp0)


def test_contrib_float_checks():
    x = nd.array([float("inf"), float("nan"), 1.0])
    onp.testing.assert_allclose(nd.contrib.isinf(x).asnumpy(), [1, 0, 0])
    onp.testing.assert_allclose(nd.contrib.isnan(x).asnumpy(), [0, 1, 0])
    onp.testing.assert_allclose(nd.contrib.isfinite(x).asnumpy(), [0, 0, 1])


def test_adamw_rejects_raw_state_arrays():
    """State args must be NDArray handles — a raw array would receive the
    in-place moment update on a throwaway wrapper and silently lose it."""
    import jax.numpy as jnp
    from mxnet_tpu.base import MXNetError as MXE
    w = nd.array(onp.ones((2,), "float32"))
    g = nd.array(onp.ones((2,), "float32"))
    with pytest.raises(MXE, match="mean"):
        nd.contrib.adamw_update(w, g, jnp.zeros(2), nd.zeros((2,)),
                                1.0, lr=0.1, eta=1.0)
