"""mx.io + mx.image tests (reference: tests/python/unittest/test_io.py,
test_image.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.io import (NDArrayIter, CSVIter, ResizeIter, PrefetchingIter,
                          ImageRecordIter)


def test_ndarrayiter_basic_and_pad():
    x = onp.arange(50, dtype="float32").reshape(10, 5)
    y = onp.arange(10, dtype="float32")
    it = NDArrayIter(x, y, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 5)
    assert batches[2].pad == 2
    it.reset()
    assert len(list(it)) == 3
    it2 = NDArrayIter(x, y, batch_size=4, last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_ndarrayiter_shuffle_covers_all():
    x = onp.arange(20, dtype="float32").reshape(20, 1)
    it = NDArrayIter(x, None, batch_size=5, shuffle=True)
    seen = onp.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(seen.tolist()) == list(range(20))


def test_csviter(tmp_path):
    data_csv = tmp_path / "d.csv"
    onp.savetxt(data_csv, onp.arange(24).reshape(8, 3), delimiter=",")
    label_csv = tmp_path / "l.csv"
    onp.savetxt(label_csv, onp.arange(8), delimiter=",")
    it = CSVIter(str(data_csv), (3,), 4, label_csv=str(label_csv))
    b = next(iter(it))
    assert b.data[0].shape == (4, 3)
    assert b.label[0].shape == (4, 1)


def test_resize_and_prefetch_iters():
    x = onp.arange(40, dtype="float32").reshape(8, 5)
    base = NDArrayIter(x, None, batch_size=4)
    r = ResizeIter(base, size=5)  # wraps around
    assert len(list(r)) == 5
    base2 = NDArrayIter(x, None, batch_size=4)
    p = PrefetchingIter(base2)
    got = list(p)
    assert len(got) == 2
    onp.testing.assert_allclose(got[0].data[0].asnumpy(), x[:4])


def test_image_record_iter(tmp_path):
    # synthetic raw-CHW payload records (imdecode_or_raw escape)
    path = str(tmp_path / "imgs.rec")
    rec = recordio.MXRecordIO(path, "w")
    rng = onp.random.RandomState(0)
    imgs = []
    for i in range(10):
        img = rng.randint(0, 255, (3, 8, 8), dtype=onp.uint8)
        imgs.append(img)
        hdr = recordio.IRHeader(flag=0, label=float(i % 3), id=i, id2=0)
        rec.write(recordio.pack(hdr, img.tobytes()))
    rec.close()

    it = ImageRecordIter(path, data_shape=(3, 8, 8), batch_size=4,
                         round_batch=True)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 3, 8, 8)
    onp.testing.assert_allclose(batches[0].data[0].asnumpy()[0],
                                imgs[0].astype("float32"))
    onp.testing.assert_allclose(batches[0].label[0].asnumpy(),
                                [0., 1., 2., 0.])
    it.reset()
    assert len(list(it)) == 3


def test_image_resize_crop_normalize():
    from mxnet_tpu import image as img
    rng = onp.random.RandomState(0)
    src = rng.randint(0, 255, (20, 30, 3)).astype("float32")
    out = img.imresize(src, 15, 10)
    assert out.shape == (10, 15, 3)
    short = img.resize_short(src, 10)
    assert min(short.shape[:2]) == 10
    c, _ = img.center_crop(src, (8, 8))
    assert c.shape == (8, 8, 3)
    rc, (x0, y0, w, h) = img.random_crop(src, (8, 8))
    assert rc.shape == (8, 8, 3) and w == 8 and h == 8
    norm = img.color_normalize(src, onp.array([128., 128., 128.]),
                               onp.array([64., 64., 64.]))
    onp.testing.assert_allclose(norm.asnumpy(),
                                (src - 128.) / 64., rtol=1e-6)


def test_augmenter_pipeline():
    from mxnet_tpu import image as img
    rng = onp.random.RandomState(1)
    src = rng.randint(0, 255, (32, 32, 3)).astype("uint8")
    augs = img.CreateAugmenter((3, 24, 24), rand_mirror=True, brightness=0.1,
                               contrast=0.1, saturation=0.1,
                               mean=True, std=True)
    out = src
    for a in augs:
        out = a(out)
    assert out.shape == (24, 24, 3)
    assert str(out.dtype) == "float32"


def test_vision_transforms_color_tail():
    """RandomHue / RandomColorJitter / RandomLighting / RandomGray
    (reference gluon/data/vision/transforms.py round-3 tail)."""
    from mxnet_tpu.gluon.data.vision import transforms as T
    from mxnet_tpu import nd as _nd
    rng = onp.random.RandomState(0)
    img = _nd.array(rng.randint(0, 255, (6, 5, 3)).astype("float32"))

    onp.random.seed(0)
    hued = T.RandomHue(0.3)(img).asnumpy()
    assert hued.shape == img.shape and onp.isfinite(hued).all()
    # hue rotation preserves luma (Y row of the YIQ matrix) closely
    coef = onp.array([0.299, 0.587, 0.114], "float32")
    onp.testing.assert_allclose((hued * coef).sum(-1),
                                (img.asnumpy() * coef).sum(-1), rtol=0.02,
                                atol=0.7)

    jit = T.RandomColorJitter(0.2, 0.2, 0.2, 0.2)
    assert jit(img).shape == img.shape

    onp.random.seed(1)
    lit = T.RandomLighting(0.1)(img).asnumpy()
    # lighting adds a constant per-channel shift
    delta = lit - img.asnumpy()
    for c in range(3):
        onp.testing.assert_allclose(delta[..., c],
                                    delta[0, 0, c], rtol=1e-5, atol=1e-4)

    gray = T.RandomGray(1.0)(img).asnumpy()
    onp.testing.assert_allclose(gray[..., 0], gray[..., 1], rtol=1e-6)
    onp.testing.assert_allclose(gray[..., 0], gray[..., 2], rtol=1e-6)
    # p=0 is identity
    onp.testing.assert_array_equal(T.RandomGray(0.0)(img).asnumpy(),
                                   img.asnumpy())


def test_bilinear_resize_2d_op():
    """nd.BilinearResize2D (+ contrib alias): size and scale modes."""
    from mxnet_tpu import nd as _nd
    x = _nd.array(onp.arange(16.0, dtype="float32").reshape(1, 1, 4, 4))
    out = _nd.BilinearResize2D(x, height=8, width=8)
    assert out.shape == (1, 1, 8, 8)
    out2 = _nd.contrib.BilinearResize2D(x, scale_height=0.5,
                                        scale_width=0.5, mode="scale")
    assert out2.shape == (1, 1, 2, 2)
    assert onp.isfinite(out2.asnumpy()).all()
    # scale mode floors (ONNX Resize convention): 5 * 1.1 -> 5
    x5 = _nd.array(onp.zeros((1, 1, 5, 5), "float32"))
    out3 = _nd.BilinearResize2D(x5, scale_height=1.1, scale_width=1.1,
                                mode="scale")
    assert out3.shape == (1, 1, 5, 5)
    from mxnet_tpu.base import MXNetError as _E
    import pytest as _pytest
    with _pytest.raises(_E):
        _nd.BilinearResize2D(x)  # size mode without height/width


def test_transforms_rotate_matches_scipy_interior():
    """Rotate kernel golden vs scipy.ndimage.rotate (bilinear,
    reshape=False): interior must agree to float tolerance; only the
    zero-padding boundary convention may differ
    (reference transforms/image.py:144 + image/image.py:618)."""
    from scipy import ndimage
    from mxnet_tpu.gluon.data.vision import transforms as T

    onp.random.seed(3)
    img = onp.random.uniform(0, 1, size=(1, 33, 37)).astype("float32")
    got = T.Rotate(30.0)(mx.nd.array(img)).asnumpy()[0]
    want = ndimage.rotate(img[0], 30.0, reshape=False, order=1,
                          mode="constant", cval=0.0)
    assert got.shape == want.shape
    onp.testing.assert_allclose(got[8:-8, 8:-8], want[8:-8, 8:-8],
                                atol=1e-4)
    with pytest.raises(TypeError):
        T.Rotate(30.0)(mx.nd.array(img.astype("int32")))


def test_transforms_rotate_zoom_flags_and_batch():
    from mxnet_tpu.gluon.data.vision import transforms as T
    from mxnet_tpu.image import imrotate

    onp.random.seed(4)
    batch = mx.nd.array(onp.random.uniform(
        0, 1, size=(3, 2, 16, 16)).astype("float32"))
    out = imrotate(batch, mx.nd.array(onp.array([10., 20., 30.],
                                                "float32")))
    assert out.shape == batch.shape
    # zoom_in crops away padding: at 45 deg every output pixel of a
    # constant image stays 1.0 (no zero padding visible)
    ones = mx.nd.array(onp.ones((1, 17, 17), "float32"))
    zin = imrotate(ones, 45.0, zoom_in=True).asnumpy()
    assert zin.min() > 0.9
    # plain rotation of the same image shows zero padding at corners
    plain = imrotate(ones, 45.0).asnumpy()
    assert plain.min() < 0.1
    with pytest.raises(ValueError):
        imrotate(ones, 45.0, zoom_in=True, zoom_out=True)
    with pytest.raises(ValueError):
        T.RandomRotation((10, -10))
    with pytest.raises(ValueError):
        T.RandomRotation((-10, 10), rotate_with_proba=1.5)


def test_transforms_random_rotation_applies_within_limits():
    from mxnet_tpu.gluon.data.vision import transforms as T

    onp.random.seed(5)
    img = mx.nd.array(onp.random.uniform(
        0, 1, size=(1, 15, 15)).astype("float32"))
    t = T.RandomRotation((-5, 5))
    out = t(img)
    assert out.shape == img.shape
    # proba=0 is identity
    t0 = T.RandomRotation((-5, 5), rotate_with_proba=0.0)
    onp.testing.assert_array_equal(t0(img).asnumpy(), img.asnumpy())


def test_transforms_crop_resize():
    from mxnet_tpu.gluon.data.vision import transforms as T

    onp.random.seed(6)
    img = mx.nd.array(onp.random.uniform(
        0, 255, size=(64, 48, 3)).astype("float32"))
    out = T.CropResize(x=4, y=8, width=32, height=16)(img)
    assert out.shape == (16, 32, 3)
    onp.testing.assert_allclose(out.asnumpy(),
                                img.asnumpy()[8:24, 4:36], rtol=1e-6)
    # with resize
    out2 = T.CropResize(x=4, y=8, width=32, height=16, size=(8, 8),
                        interpolation=1)(img)
    assert out2.shape == (8, 8, 3)
    # batch
    b = mx.nd.array(onp.random.uniform(
        0, 255, size=(2, 64, 48, 3)).astype("float32"))
    out3 = T.CropResize(x=0, y=0, width=10, height=12, size=(5, 6))(b)
    assert out3.shape == (2, 6, 5, 3)


def test_transforms_compose_hybrid_and_random_apply():
    from mxnet_tpu.gluon.data.vision import transforms as T

    onp.random.seed(7)
    img = mx.nd.array(onp.random.uniform(
        0, 255, size=(32, 32, 3)).astype("float32"))
    hc = T.HybridCompose([T.CropResize(0, 0, 16, 16),
                          T.CropResize(2, 2, 8, 8)])
    out = hc(img)
    assert out.shape == (8, 8, 3)
    # non-hybrid member rejected
    with pytest.raises(ValueError):
        T.HybridCompose([T.CropResize(0, 0, 16, 16), T.ToTensor()])

    # RandomApply: p=1 always applies, p=0 never
    always = T.RandomApply(T.CropResize(0, 0, 16, 16), p=1.0)
    assert always(img).shape == (16, 16, 3)
    never = T.RandomApply(T.CropResize(0, 0, 16, 16), p=0.0)
    assert never(img).shape == (32, 32, 3)


def test_transforms_hybrid_random_apply_cond():
    """HybridRandomApply: device-side coin + lax.cond branch — shapes
    must match between branches (the reference F.contrib.cond contract),
    so use a shape-preserving hybrid transform."""
    from mxnet_tpu.gluon.data.vision import transforms as T

    class Scale(mx.gluon.HybridBlock):
        def forward(self, x):
            return x * 2.0

    img = mx.nd.array(onp.ones((4, 4, 3), "float32"))
    seen = set()
    for i in range(20):
        out = T.HybridRandomApply(Scale(), p=0.5)(img).asnumpy()
        seen.add(float(out.ravel()[0]))
    assert seen <= {1.0, 2.0} and len(seen) == 2
    with pytest.raises(AssertionError):
        T.HybridRandomApply(T.ToTensor(), p=0.5)


def test_hybrid_random_apply_probability_direction():
    """``p`` is the probability of APPLYING the transform (the seed had
    it inverted: applied with 1-p). Directional check with p near 0 and
    1: at p=0.05 the transform must fire rarely, at p=0.95 almost
    always."""
    from mxnet_tpu.gluon.data.vision import transforms as T

    class Scale(mx.gluon.HybridBlock):
        def forward(self, x):
            return x * 2.0

    img = mx.nd.array(onp.ones((2, 2, 3), "float32"))
    # n=80 keeps the direction unambiguous under the fixed seed while
    # staying cheap (each draw is an eager device round-trip)
    n = 80
    for p, lo, hi in ((0.05, 0.0, 0.3), (0.95, 0.7, 1.0)):
        mx.random.seed(42)
        tf = T.HybridRandomApply(Scale(), p=p)
        applied = sum(
            float(tf(img).asnumpy().ravel()[0]) == 2.0 for _ in range(n))
        frac = applied / n
        assert lo <= frac <= hi, \
            f"p={p}: applied fraction {frac} outside [{lo}, {hi}]"
