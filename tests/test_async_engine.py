"""Async dispatch engine (ISSUE 5): pipelined train steps, device-side
input prefetch, sync-free metrics.

Acceptance bar, all counter-based (never wall-clock):

- the dispatcher never blocks until ``MXNET_INFLIGHT_STEPS`` futures are
  outstanding (DispatchWindow unit counters + a jax.block_until_ready
  census over a real pipelined TrainLoop);
- prefetched batches land with the step's exact sharding (dp-sharded
  batch dim on a mesh when divisible, replicated otherwise, default
  device placement without a mesh);
- a faulting step N raises at or before the sync of step N — named as
  step N — never silently at N+k with the wrong traceback;
- bit-exact loss parity pipelined-vs-synchronous for sgd-mom/adam ×
  fused/zero;
- with MXNET_TRANSFER_GUARD=raise a pipelined >=10-step TrainLoop run
  performs ZERO unblessed host syncs inside the hot loop (the guard IS
  the regression test);
- metric accumulators run sync-free on device inputs and match the host
  float64 path;
- MXNET_COMPILE_CACHE arms jax's persistent compilation cache.
"""
import os

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import engine, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.analysis import guard as tguard
from mxnet_tpu.gluon import Trainer, TrainLoop, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher
from mxnet_tpu.parallel import make_mesh


def _build(seed=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(3, in_units=8))
    net.initialize()
    return net


def _batch(bs=8, seed=0):
    rng = onp.random.RandomState(seed)
    x = nd.array(rng.randn(bs, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(bs,)).astype("int32"))
    return x, y


# ---------------------------------------------------------------------------
# DispatchWindow semantics (pure counters, injected sync_fn)
# ---------------------------------------------------------------------------

def test_window_never_blocks_until_full():
    """PushAsync contract: with window W, pushes 1..W trigger ZERO
    retires; push W+1 retires exactly the oldest. FIFO order."""
    synced = []
    w = engine.DispatchWindow(max_inflight=3, sync_fn=synced.append)
    for i in range(3):
        w.push(f"p{i}", tag=i)
        assert synced == [], f"blocked early at push {i}"
    assert len(w) == 3
    w.push("p3", tag=3)
    assert synced == ["p0"]          # oldest only
    for i in range(4, 10):
        w.push(f"p{i}", tag=i)
    assert synced == [f"p{i}" for i in range(7)]
    w.drain()
    assert synced == [f"p{i}" for i in range(10)]
    assert w.stats["pushes"] == 10 and w.stats["retires"] == 10
    assert len(w) == 0


def test_window_zero_is_synchronous_oracle():
    synced = []
    w = engine.DispatchWindow(max_inflight=0, sync_fn=synced.append)
    for i in range(4):
        w.push(i, tag=i)
        assert synced == list(range(i + 1)), "window 0 must sync per push"


def test_window_error_attributed_to_faulting_step():
    """A fault in step 3 must raise when step 3 retires (at push 3+W) —
    named as step 3 — and the window must stay usable after."""
    def sync(payload):
        if payload == "boom3":
            raise RuntimeError("device exploded")

    w = engine.DispatchWindow(max_inflight=2, sync_fn=sync)
    payloads = ["ok0", "ok1", "ok2", "boom3", "ok4", "ok5"]
    raised_at = None
    for i, p in enumerate(payloads):
        try:
            w.push(p, tag=i)
        except MXNetError as e:
            raised_at = i
            assert "3" in str(e) and "device exploded" in str(e)
            break
    # retire of step 3 happens at push 5 (window 2) — at or before the
    # sync of step 3, never later
    assert raised_at == 5
    assert w.stats["errors"] == 1
    w.push("ok6", tag=6)            # engine remains usable post-error
    w.drain()


def test_window_error_surfaces_on_drain():
    def sync(payload):
        if payload == "bad":
            raise RuntimeError("late fault")

    w = engine.DispatchWindow(max_inflight=8, sync_fn=sync)
    w.push("fine", tag=1)
    w.push("bad", tag=2)
    with pytest.raises(MXNetError, match="2"):
        w.drain()
    w.drain()                       # remains usable; nothing pending
    assert len(w) == 0


def test_inflight_steps_env_and_naive(monkeypatch):
    monkeypatch.setenv("MXNET_INFLIGHT_STEPS", "5")
    assert engine.inflight_steps() == 5
    monkeypatch.setenv("MXNET_INFLIGHT_STEPS", "not-a-number")
    assert engine.inflight_steps() == 2
    monkeypatch.setenv("MXNET_INFLIGHT_STEPS", "-3")
    assert engine.inflight_steps() == 0
    # NaiveEngine forces the synchronous oracle regardless of the window
    prev = engine.Engine._instance
    try:
        engine.Engine._instance = engine.Engine("NaiveEngine")
        monkeypatch.setenv("MXNET_INFLIGHT_STEPS", "7")
        assert engine.inflight_steps() == 0
    finally:
        engine.Engine._instance = prev


# ---------------------------------------------------------------------------
# TrainLoop pipelining (counter-based over the real jit path)
# ---------------------------------------------------------------------------

def test_train_loop_dispatch_counters():
    """Over N steps with window W: retires observed DURING the loop are
    exactly N - W (each over-capacity push retires one), and the N
    async losses were pushed without the loop ever forcing them."""
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=2)
    x, y = _batch()
    tguard.reset_sync_counts()
    for _ in range(7):
        loop.step(x, y)
    counts = tguard.sync_counts()
    assert counts.get("window_retire", 0) == 5      # 7 - W
    assert counts.get("wait_to_read", 0) == 0, \
        "the pipelined loop must not force the loss"
    assert loop.engine_stats()["pending"] == 2
    loop.synchronize()
    assert tguard.sync_counts()["window_retire"] == 7
    assert loop.engine_stats()["pending"] == 0
    s = loop.engine_stats()
    assert s["pushes"] == 7 and s["inflight_window"] == 2


def test_train_loop_inflight_env_default(monkeypatch):
    monkeypatch.setenv("MXNET_INFLIGHT_STEPS", "4")
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss())
    assert loop.engine_stats()["inflight_window"] == 4


def test_waitall_drains_train_loop_window():
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=4)
    x, y = _batch()
    for _ in range(3):
        loop.step(x, y)
    assert loop.engine_stats()["pending"] == 3
    nd.waitall()
    assert loop.engine_stats()["pending"] == 0


# ---------------------------------------------------------------------------
# device prefetch: sharding + overlap machinery
# ---------------------------------------------------------------------------

def test_prefetcher_default_device_placement():
    rng = onp.random.RandomState(0)
    host = [(rng.randn(8, 4).astype("float32"),
             rng.randint(0, 3, size=(8,)).astype("int32"))
            for _ in range(4)]
    pf = DevicePrefetcher(iter(host), depth=2)
    out = list(pf)
    assert len(out) == 4
    for (hx, hy), (dx, dy) in zip(host, out):
        assert isinstance(dx, jax.Array) and isinstance(dy, jax.Array)
        onp.testing.assert_array_equal(onp.asarray(dx), hx)
        onp.testing.assert_array_equal(onp.asarray(dy), hy)
    assert pf.stats["prefetch_batches"] == 4


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
def test_prefetcher_mesh_sharding():
    """Batches land with the fused step's exact layout: dim0 divisible
    by dp → batch-sharded NamedSharding; non-divisible → replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh({"dp": 4}, jax.devices()[:4])
    rng = onp.random.RandomState(0)
    divisible = nd.array(rng.randn(8, 4).astype("float32"))
    ragged = nd.array(rng.randn(6, 4).astype("float32"))
    pf = DevicePrefetcher(iter([(divisible, ragged)]), depth=2, mesh=mesh)
    (dx, dr), = list(pf)
    assert isinstance(dx, nd.NDArray) and isinstance(dr, nd.NDArray)
    assert isinstance(dx._data.sharding, NamedSharding)
    assert dx._data.sharding.spec == P("dp", None)
    assert dr._data.sharding.spec == P()        # replicated fallback
    onp.testing.assert_array_equal(dx.asnumpy(), divisible.asnumpy())


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
def test_train_loop_prefetch_uses_step_sharding():
    """loop.prefetch stages with CompiledTrainStep.input_placement —
    under an active dp mesh the batch arrives pre-sharded and the fused
    step's own placement passes it through untouched."""
    from jax.sharding import PartitionSpec as P
    with make_mesh({"dp": 4}, jax.devices()[:4]):
        net = _build()
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9})
        loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss())
        x, y = _batch(bs=8)
        seen = []
        for bx, by in loop.prefetch((x, y) for _ in range(3)):
            seen.append(bx._data.sharding.spec)
            loop.step(bx, by)
        loop.synchronize()
    assert seen == [P("dp", None)] * 3
    assert loop.compiled_step.mode == "fused"


def test_prefetcher_propagates_worker_error():
    def batches():
        yield onp.zeros((2, 2), "float32")
        raise ValueError("dataset exploded")

    pf = DevicePrefetcher(batches(), depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(ValueError, match="dataset exploded"):
        next(it)


def test_prefetcher_early_break_stops_producer():
    produced = []

    def batches():
        for i in range(1000):
            produced.append(i)
            yield onp.full((2,), i, "float32")

    pf = DevicePrefetcher(batches(), depth=2)
    for i, b in enumerate(pf):
        if i == 2:
            break
    # bounded staging: the producer cannot have run far ahead of the
    # depth-2 queue (+1 in-hand +1 being staged)
    assert len(produced) <= 2 + 2 + 2


def test_dataloader_device_prefetch():
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import ArrayDataset
    rng = onp.random.RandomState(0)
    xs = rng.randn(32, 4).astype("float32")
    ys = rng.randint(0, 3, size=(32,)).astype("int32")
    ds = ArrayDataset(xs, ys)
    plain = [tuple(b.asnumpy() for b in batch)
             for batch in DataLoader(ds, batch_size=8)]
    dl = DataLoader(ds, batch_size=8, device=True, prefetch_to_device=2)
    staged = list(dl)
    assert len(staged) == len(plain) == 4
    for (px, py), (sx, sy) in zip(plain, staged):
        assert isinstance(sx, nd.NDArray)
        assert isinstance(sx._data, jax.Array)
        onp.testing.assert_array_equal(sx.asnumpy(), px)
        onp.testing.assert_array_equal(sy.asnumpy(), py)
    stats = dl.device_prefetch_stats
    assert stats is not None and stats["prefetch_batches"] == 4


# ---------------------------------------------------------------------------
# parity: pipelined vs synchronous must be bit-exact
# ---------------------------------------------------------------------------

def _run_loop(opt, kwargs, inflight, steps=6, mesh_ctx=None, prefetch=False):
    net = _build(seed=11)
    trainer = Trainer(net.collect_params(), opt, dict(kwargs))
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=inflight)
    x, y = _batch(bs=8, seed=5)
    losses = []
    if prefetch:
        for bx, by in loop.prefetch((x, y) for _ in range(steps)):
            losses.append(loop.step(bx, by))
    else:
        for _ in range(steps):
            losses.append(loop.step(x, y))
    loop.synchronize()
    # host reads AFTER the run — the values were async the whole time
    vals = [l.asnumpy() for l in losses]
    params = {k: p.data().asnumpy()
              for k, p in net.collect_params().items()}
    return vals, params, loop


@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_pipelined_parity_fused(opt, kwargs):
    sync_vals, sync_params, sloop = _run_loop(opt, kwargs, inflight=0)
    pipe_vals, pipe_params, ploop = _run_loop(opt, kwargs, inflight=3,
                                              prefetch=True)
    assert sloop.compiled_step.mode == "fused"
    assert ploop.compiled_step.mode == "fused"
    for a, b in zip(sync_vals, pipe_vals):
        onp.testing.assert_array_equal(a, b)   # BIT-exact
    for k in sync_params:
        onp.testing.assert_array_equal(sync_params[k], pipe_params[k])


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_pipelined_parity_zero_sharded(opt, kwargs):
    with make_mesh({"dp": 4}, jax.devices()[:4]):
        sync_vals, sync_params, sloop = _run_loop(opt, kwargs, inflight=0)
    with make_mesh({"dp": 4}, jax.devices()[:4]):
        pipe_vals, pipe_params, ploop = _run_loop(opt, kwargs, inflight=3,
                                                  prefetch=True)
    assert sloop.compiled_step.zero_sharded
    assert ploop.compiled_step.zero_sharded
    for a, b in zip(sync_vals, pipe_vals):
        onp.testing.assert_array_equal(a, b)
    for k in sync_params:
        onp.testing.assert_array_equal(sync_params[k], pipe_params[k])


# ---------------------------------------------------------------------------
# the transfer guard IS the regression test (acceptance criterion)
# ---------------------------------------------------------------------------

def test_pipelined_loop_zero_unblessed_syncs(monkeypatch):
    """MXNET_TRANSFER_GUARD=raise + a pipelined >=10-step prefetched run:
    the ONLY host syncs are the blessed window retires. Any unblessed
    sync inside the hot loop raises and fails this test."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    net = _build()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loop = TrainLoop(net, trainer, gloss.SoftmaxCrossEntropyLoss(),
                     inflight=2)
    x, y = _batch()
    tguard.reset_sync_counts()
    tguard.clear_events()
    losses = []
    for bx, by in loop.prefetch((x, y) for _ in range(12)):
        losses.append(loop.step(bx, by))   # raises on any unblessed sync
    loop.synchronize()
    assert loop.compiled_step.mode == "fused"
    counts = tguard.sync_counts()
    assert counts.get("wait_to_read", 0) == 0
    assert counts.get("window_retire", 0) == 12
    assert tguard.events() == []
    # outside the hot loop the values read freely
    assert onp.isfinite(losses[-1].asnumpy()).all()


def test_guard_flags_hostile_sync_in_pipelined_loop(monkeypatch):
    """Negative control: a loss_fn that syncs (float/asnumpy) inside the
    hot loop must RAISE under the armed guard, not silently demote the
    run to one device round-trip per step."""
    monkeypatch.setenv("MXNET_TRANSFER_GUARD", "raise")
    net = _build()
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})

    def hostile(a, b):
        out = net(a)
        _ = float(out.asnumpy().sum())     # the classic silent stall
        return loss_blk(out, b)

    step = trainer.compile_step(hostile)
    x, y = _batch()
    with pytest.raises(MXNetError, match="hot region"):
        step(x, y)


# ---------------------------------------------------------------------------
# sync-free metrics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("factory,binary", [
    (lambda m: m.Accuracy(), False),
    (lambda m: m.TopKAccuracy(top_k=2), False),
    (lambda m: m.MAE(), None),
    (lambda m: m.MSE(), None),
    (lambda m: m.RMSE(), None),
    (lambda m: m.CrossEntropy(), False),
    (lambda m: m.Perplexity(), False),
    (lambda m: m.F1(), True),
    (lambda m: m.MCC(), True),
    (lambda m: m.BinaryAccuracy(), True),
    (lambda m: m.MeanPairwiseDistance(), None),
    (lambda m: m.MeanCosineSimilarity(), None),
])
def test_metric_device_accumulation_sync_free(factory, binary):
    """Two batches through each metric: the device path performs ZERO
    host syncs during update (proven by the armed guard) and get()
    matches the host float64 path."""
    from mxnet_tpu import metric
    rng = onp.random.RandomState(7)
    batches = []
    for seed in (0, 1):
        r = onp.random.RandomState(seed)
        if binary is None:                     # regression-style
            label = r.randn(16, 4).astype("float32")
            pred = r.randn(16, 4).astype("float32")
        elif binary:                           # {0,1} labels, 2-col pred
            label = r.randint(0, 2, size=(16,)).astype("int64")
            pred = r.rand(16, 2).astype("float32")
            if isinstance(factory(metric), metric.BinaryAccuracy):
                pred = r.rand(16).astype("float32")
        else:                                  # 3-class
            label = r.randint(0, 3, size=(16,)).astype("int64")
            pred = r.rand(16, 3).astype("float32")
            pred /= pred.sum(-1, keepdims=True)
        batches.append((label, pred))
    del rng

    m_host, m_dev = factory(metric), factory(metric)
    for label, pred in batches:
        m_host.update(label, pred)
    with tguard.transfer_guard("raise", scope="metric.update"):
        for label, pred in batches:
            m_dev.update(nd.array(label), nd.array(pred))
    name_h, v_host = m_host.get()
    name_d, v_dev = m_dev.get()
    assert name_h == name_d
    assert m_dev.num_inst == m_host.num_inst
    onp.testing.assert_allclose(v_dev, v_host, rtol=1e-4, atol=1e-5)


def test_metric_loss_device_sync_free():
    from mxnet_tpu import metric
    r = onp.random.RandomState(0)
    v = r.randn(8, 3).astype("float32")
    m_host, m_dev = metric.Loss(), metric.Loss()
    m_host.update(None, v)
    with tguard.transfer_guard("raise"):
        m_dev.update(None, nd.array(v))
    onp.testing.assert_allclose(m_dev.get()[1], m_host.get()[1],
                                rtol=1e-5)


def test_metric_host_path_unchanged():
    """Numpy inputs keep the reference float64 host accumulation — no
    device arrays appear in the accumulator."""
    from mxnet_tpu import metric
    m = metric.Accuracy()
    m.update(onp.array([0, 1, 1]), onp.array([[1, 0], [0, 1], [1, 0]],
                                             "float32"))
    assert isinstance(m.sum_metric, float)
    assert m.get()[1] == pytest.approx(2.0 / 3.0)


# ---------------------------------------------------------------------------
# persistent compile cache (MXNET_COMPILE_CACHE)
# ---------------------------------------------------------------------------

def test_compile_cache_armed(tmp_path, monkeypatch):
    import jax as _jax
    from mxnet_tpu import runtime
    cache_dir = tmp_path / "xla-cache"
    monkeypatch.setenv("MXNET_COMPILE_CACHE", str(cache_dir))
    monkeypatch.setitem(runtime._CACHE_STATS, "enabled", False)
    prev_dir = _jax.config.jax_compilation_cache_dir
    try:
        assert runtime.setup_compile_cache() is True
        stats = runtime.compile_cache_stats()
        assert stats["enabled"] and stats["dir"] == str(cache_dir)
        assert _jax.config.jax_compilation_cache_dir == str(cache_dir)
        assert os.path.isdir(cache_dir)
        # idempotent re-arm
        assert runtime.setup_compile_cache() is True
    finally:
        # un-pollute process-global jax config for the rest of tier-1
        _jax.config.update("jax_compilation_cache_dir", prev_dir)
        runtime._CACHE_STATS.update(enabled=False, dir=None)


def test_compile_cache_off_without_env(monkeypatch):
    from mxnet_tpu import runtime
    monkeypatch.delenv("MXNET_COMPILE_CACHE", raising=False)
    monkeypatch.setitem(runtime._CACHE_STATS, "enabled", False)
    assert runtime.setup_compile_cache() is False
