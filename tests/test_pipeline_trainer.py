"""PipelineTrainer: Gluon GPipe integration (VERDICT r2 item 9) — the
pipelined Trainer's losses match the single-device Trainer, grads land on
Parameters, and bad partitions are rejected."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


def _make_net(width=16, depth=4, seed_base=7):
    net = nn.HybridSequential()
    for _ in range(depth):
        net.add(nn.Dense(width, activation="tanh", in_units=width))
    net.initialize()
    for i, p in enumerate(net.collect_params().values()):
        p.set_data(nd.array(
            onp.random.RandomState(seed_base * i + 1)
            .uniform(-0.4, 0.4, p.shape).astype("float32")))
    return net


def _data(width=16, batch=16):
    rng = onp.random.RandomState(1)
    return (rng.randn(batch, width).astype("float32"),
            rng.randn(batch, width).astype("float32"))


@pytest.mark.parametrize("opt,opt_args", [
    ("sgd", {"learning_rate": 0.1}),
    ("adam", {"learning_rate": 0.01}),
])
def test_pipeline_losses_match_single_device(opt, opt_args):
    x, y = _data()
    ref = _make_net()
    tr_ref = gluon.Trainer(ref.collect_params(), opt, dict(opt_args))
    ref_losses = []
    for _ in range(5):
        with autograd.record():
            loss = ((ref(nd.array(x)) - nd.array(y)) ** 2).mean()
        loss.backward()
        tr_ref.step(1)
        ref_losses.append(float(loss.asnumpy()))

    net = _make_net()
    tr = gluon.PipelineTrainer(net, opt, dict(opt_args),
                               num_stages=4, num_microbatches=4)
    pp_losses = []
    for _ in range(5):
        loss = tr.forward_backward(nd.array(x), nd.array(y))
        tr.step(1)
        pp_losses.append(float(loss.asnumpy()))
    onp.testing.assert_allclose(pp_losses, ref_losses, rtol=3e-4)
    # weights converged identically too
    for pr, pp in zip(ref.collect_params().values(),
                      net.collect_params().values()):
        onp.testing.assert_allclose(pp.data().asnumpy(),
                                    pr.data().asnumpy(), rtol=2e-3,
                                    atol=1e-5)


def test_pipeline_multi_block_stages_and_custom_loss():
    # 8 blocks into 4 stages of 2; explicit Gluon loss object
    x, y = _data()
    net = _make_net(depth=8)
    l2 = gluon.loss.L2Loss()
    tr = gluon.PipelineTrainer(net, "sgd", {"learning_rate": 0.05},
                               num_stages=4, num_microbatches=2, loss=l2)
    first = float(tr.forward_backward(nd.array(x), nd.array(y)).asnumpy())
    tr.step(1)
    for _ in range(4):
        loss = tr.forward_backward(nd.array(x), nd.array(y))
        tr.step(1)
    assert float(loss.asnumpy()) < first


def test_pipeline_grads_land_on_parameters():
    x, y = _data()
    net = _make_net()
    tr = gluon.PipelineTrainer(net, "sgd", {"learning_rate": 0.1},
                               num_stages=4, num_microbatches=4)
    tr.forward_backward(nd.array(x), nd.array(y))
    for p in net.collect_params().values():
        g = p.grad().asnumpy()
        assert onp.isfinite(g).all()
        assert onp.abs(g).max() > 0, p.name


def test_pipeline_rejects_bad_partitions():
    net = _make_net(depth=4)
    with pytest.raises(MXNetError):
        gluon.PipelineTrainer(net, "sgd", num_stages=3)
    bad = nn.HybridSequential()
    bad.add(nn.Dense(8, in_units=16), nn.Dense(16, in_units=8))
    bad.initialize()
    with pytest.raises(MXNetError):
        gluon.PipelineTrainer(bad, "sgd", num_stages=2)  # shapes differ
    empty = nn.HybridSequential()
    with pytest.raises(MXNetError):
        gluon.PipelineTrainer(empty, "sgd")
