"""tools/ (launch, im2rec) + mx.rtc + onnx gating tests.

Reference analogs: tests/nightly dist launch rigs (`tools/launch.py -n N
--launcher local`, SURVEY §4) and test_rtc.py.
"""
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_launch_local_multiprocess(tmp_path):
    # 3 workers each write rank/size read from the DMLC_* env contract
    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        f"open(r'{tmp_path}' + '/out' + os.environ['DMLC_WORKER_ID'], 'w')"
        ".write(os.environ['DMLC_NUM_WORKER'])\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "3", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, timeout=60)
    assert r.returncode == 0, r.stderr.decode()
    for i in range(3):
        assert (tmp_path / f"out{i}").read_text() == "3"


def test_im2rec_list_and_pack(tmp_path):
    # tiny image tree with raw files (no PIL needed for packing)
    for cls in ("cat", "dog"):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(3):
            (d / f"{i}.jpg").write_bytes(os.urandom(64))
    prefix = str(tmp_path / "ds")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2rec.py"),
         prefix, str(tmp_path / "imgs"), "--no-shuffle"],
        capture_output=True, timeout=60)
    assert r.returncode == 0, r.stderr.decode()
    assert os.path.exists(prefix + ".rec") and os.path.exists(prefix + ".idx")
    rec = mx.recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(rec.keys) == 6
    hdr, payload = mx.recordio.unpack(rec.read_idx(rec.keys[0]))
    assert len(payload) == 64
    labels = sorted({float(mx.recordio.unpack(rec.read_idx(k))[0].label)
                     for k in rec.keys})
    assert labels == [0.0, 1.0]


def test_rtc_pallas_module():
    src = """
def scale_add(x, y, alpha=2.0):
    return x * alpha + y
"""
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("scale_add")
    x = mx.nd.array(onp.ones((4,), "float32"))
    y = mx.nd.array(onp.arange(4, dtype="float32"))
    out = k.launch(x, y, alpha=3.0)
    onp.testing.assert_allclose(out.asnumpy(), 3.0 + onp.arange(4))
    with pytest.raises(MXNetError, match="not found"):
        mod.get_kernel("nope")


def test_rtc_pallas_kernel_real():
    # an actual pallas_call kernel through the interpreter
    src = """
from jax.experimental import pallas as pl

def _double_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0

def double(x):
    return pl.pallas_call(
        _double_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=True)(x)
"""
    mod = mx.rtc.PallasModule(src)
    out = mod.get_kernel("double").launch(mx.nd.array(onp.ones((8, 128),
                                                               "float32")))
    onp.testing.assert_allclose(out.asnumpy(), 2 * onp.ones((8, 128)))


def test_cuda_module_redirects():
    with pytest.raises(MXNetError, match="PallasModule"):
        mx.rtc.CudaModule("__global__ void k() {}")


def test_onnx_rejects_non_symbol():
    # real serializer now (tests/test_onnx.py); non-Symbol input must raise
    from mxnet_tpu.contrib import onnx as mxonnx
    with pytest.raises(MXNetError, match="Symbol"):
        mxonnx.export_model(None, None)


def test_launch_local_kills_siblings_on_failure(tmp_path):
    # one worker exits 1 immediately; a sibling sleeps forever — launcher
    # must terminate it and return nonzero instead of hanging
    script = tmp_path / "w.py"
    script.write_text(
        "import os, sys, time\n"
        "if os.environ['DMLC_WORKER_ID'] == '0':\n"
        "    sys.exit(1)\n"
        "time.sleep(600)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, timeout=30)
    assert r.returncode != 0


def test_rtc_ignores_imported_callables():
    mod = mx.rtc.PallasModule(
        "from functools import partial\n"
        "import math\n"
        "def real_kernel(x):\n"
        "    return x + 1\n")
    assert sorted(mod._kernels) == ["real_kernel"]
