"""SPMD sharding static analysis (mx.analysis.sharding, PR 13):
OpSharding grammar golden cases, mesh-axis resolution, the
sharding-flow table over the real zero-sharded step, implicit-reshard
detection (planted mismatched-PartitionSpec program with correct byte
counts), the per-axis ring-model communication cost, bandwidth-profile
parsing, the expect_spec invariant packs (zero / tp-attention /
sp-ring-attention here; ep-moe / pp-gpipe in test_moe_pipeline.py),
the sharding baseline regression gate (tier-1 ``lint``-marked sweep at
the bottom + analyze='raise' injected-regression), the SPMD per-shard
fusion-census accounting, and the MXA006 source-lint rule.
"""
import json
import os
import textwrap

import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.analysis import sharding as asharding
from mxnet_tpu.analysis.fusion import fusion_census
from mxnet_tpu.analysis.hlo import parse_hlo, parse_source_target_pairs
from mxnet_tpu.analysis.lint import lint_source
from mxnet_tpu.analysis.program import (analyze_lowered,
                                        collective_census, expect_mode,
                                        mode_spec_pack)
from mxnet_tpu.analysis.report import CollectiveOp, CollectiveStats
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import make_mesh, shard_batch

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
BASELINES = os.path.join(FIXTURES, "sharding_baselines.json")

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")

DP = 4


# ---------------------------------------------------------------------------
# OpSharding grammar
# ---------------------------------------------------------------------------

def test_parse_replicated_manual_maximal():
    assert asharding.parse_op_sharding("{replicated}").kind == \
        "replicated"
    assert asharding.parse_op_sharding("{manual}").kind == "manual"
    m = asharding.parse_op_sharding("{maximal device=3}")
    assert m.kind == "maximal" and m.maximal_device == 3
    for sh in (asharding.parse_op_sharding("{replicated}"),
               asharding.parse_op_sharding("{manual}")):
        assert sh.shard_count == 1
        assert sh.local_shape((8, 4)) == (8, 4)


def test_parse_iota_tiled():
    sh = asharding.parse_op_sharding("{devices=[4,1]<=[4]}")
    assert sh.kind == "tiled"
    assert sh.tile_dims == (4, 1)
    assert sh.device_order == (0, 1, 2, 3)
    assert sh.shard_count == 4
    assert sh.local_shape((8, 16)) == (2, 16)
    assert sh.global_shape((2, 16)) == (8, 16)
    # ceil-divide on uneven dims, as GSPMD pads
    assert sh.local_shape((7, 16)) == (2, 16)


def test_parse_iota_transposed():
    sh = asharding.parse_op_sharding("{devices=[2,2]<=[2,2]T(1,0)}")
    # arange(4).reshape(2,2).T.flatten() == [0, 2, 1, 3]
    assert sh.device_order == (0, 2, 1, 3)


def test_parse_explicit_device_list():
    sh = asharding.parse_op_sharding("{devices=[2,2]0,2,1,3}")
    assert sh.kind == "tiled" and sh.device_order == (0, 2, 1, 3)
    # wrong-arity explicit list degrades to no order, not an exception
    bad = asharding.parse_op_sharding("{devices=[2,2]0,1}")
    assert bad.device_order is None


def test_parse_partial_replication():
    sh = asharding.parse_op_sharding(
        "{devices=[2,1,2]<=[4] last_tile_dim_replicate}")
    assert sh.n_subgroup_dims == 1
    assert sh.data_tile_dims == (2, 1)
    assert sh.shard_count == 2
    assert sh.local_shape((8, 4)) == (4, 4)


def test_parse_tuple_sharding():
    sh = asharding.parse_op_sharding(
        "{{replicated}, {devices=[4]<=[4]}}")
    assert sh.kind == "tuple" and len(sh.parts) == 2
    assert sh.parts[0].kind == "replicated"
    assert sh.parts[1].shard_count == 4


def test_parse_garbage_degrades():
    assert asharding.parse_op_sharding(None) is None
    assert asharding.parse_op_sharding("") is None
    assert asharding.parse_op_sharding("{what=even}").kind == "unknown"


# ---------------------------------------------------------------------------
# mesh-axis resolution
# ---------------------------------------------------------------------------

@needs_mesh
def test_resolve_1d_dp():
    mesh = make_mesh({"dp": DP}, jax.devices()[:DP])
    sh = asharding.parse_op_sharding("{devices=[4,1]<=[4]}")
    assert sh.resolve(mesh) == ("dp", None)
    assert sh.describe() == "P(dp, -)"


@needs_mesh
def test_resolve_2d_and_transposed():
    mesh = make_mesh({"dp": 2, "tp": 2}, jax.devices()[:4])
    sh = asharding.parse_op_sharding("{devices=[2,2]<=[4]}")
    assert sh.resolve(mesh) == ("dp", "tp")
    tr = asharding.parse_op_sharding("{devices=[2,2]<=[2,2]T(1,0)}")
    assert tr.resolve(mesh) == ("tp", "dp")


@needs_mesh
def test_resolve_foreign_world_is_unresolved():
    """An annotation naming device ids outside the mesh resolves to
    None, never raises."""
    mesh = make_mesh({"dp": 2}, jax.devices()[:2])
    sh = asharding.parse_op_sharding("{devices=[4,1]<=[4]}")
    assert sh.resolve(mesh) is None


def test_source_target_pairs_connected_components():
    groups = parse_source_target_pairs(
        "x, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}")
    assert groups == [(0, 1, 2, 3)]
    two = parse_source_target_pairs(
        "x, source_target_pairs={{0,1},{1,0},{2,3},{3,2}}")
    assert sorted(two) == [(0, 1), (2, 3)]


# ---------------------------------------------------------------------------
# sharding table (canned partitioned module + StableHLO global shapes)
# ---------------------------------------------------------------------------

_CANNED_SPMD = textwrap.dedent("""\
HloModule jit_f, is_scheduled=true, entry_computation_layout={(f32[2,16]{1,0}, f32[16,8]{1,0})->f32[2,8]{1,0}}, num_partitions=4

ENTRY %main.5_spmd (param: f32[2,16], param.1: f32[16,8]) -> f32[2,8] {
  %param = f32[2,16]{1,0} parameter(0), sharding={devices=[4,1]<=[4]}, metadata={op_name="x"}
  %param.1 = f32[16,8]{1,0} parameter(1), sharding={replicated}, metadata={op_name="w"}
  ROOT %dot = f32[2,8]{1,0} dot(f32[2,16]{1,0} %param, f32[16,8]{1,0} %param.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
""")

_CANNED_STABLEHLO = (
    'func.func public @main(%arg0: tensor<8x16xf32> {mhlo.sharding = '
    '"{devices=[4,1]<=[4]}"}, %arg1: tensor<16x8xf32> {mhlo.sharding = '
    '"{replicated}"}) -> tensor<8x8xf32>')


@needs_mesh
def test_sharding_table_canned():
    mesh = make_mesh({"dp": DP}, jax.devices()[:DP])
    tbl = asharding.sharding_table(_CANNED_SPMD, mesh=mesh,
                                   stablehlo=_CANNED_STABLEHLO)
    assert tbl.num_partitions == 4
    x, w = tbl.params
    assert x.name == "x" and x.local_shape == (2, 16)
    assert x.global_shape == (8, 16)            # from the StableHLO side
    assert x.sharding.spec == ("dp", None)
    assert x.bytes_local == 2 * 16 * 4
    assert x.bytes_global == 8 * 16 * 4
    assert w.sharding.kind == "replicated"
    assert w.global_shape == (16, 8)
    assert "P(dp, -)" in tbl.table_str()


def test_sharding_table_without_mesh_or_stablehlo():
    """No mesh, no StableHLO: global shape = local x tile dims, spec
    unresolved — degraded, never raised."""
    tbl = asharding.sharding_table(_CANNED_SPMD)
    x = tbl.params[0]
    assert x.global_shape == (8, 16)           # local (2,16) x tile 4
    assert x.sharding.spec is None


def test_stablehlo_shardings_parse():
    got = asharding.stablehlo_shardings(_CANNED_STABLEHLO)
    assert got[0][0] == (8, 16) and got[0][1] == "f32"
    assert got[0][2].shard_count == 4
    assert got[1][0] == (16, 8)
    assert got[1][2].kind == "replicated"


def test_table_digest_is_stable_and_layout_sensitive():
    a = asharding.sharding_table(_CANNED_SPMD)
    b = asharding.sharding_table(_CANNED_SPMD)
    assert a.digest() == b.digest()
    mutated = _CANNED_SPMD.replace("{devices=[4,1]<=[4]}",
                                   "{replicated}")
    assert asharding.sharding_table(mutated).digest() != a.digest()


# ---------------------------------------------------------------------------
# communication cost model
# ---------------------------------------------------------------------------

def _cop(kind, elements, group=4, dtype="f32", decomposed=False,
         axes=("dp",), name="c"):
    return CollectiveOp(kind=kind, name=name, elements=elements,
                        dtype=dtype, axes=axes, group_size=group,
                        decomposed=decomposed)


def test_wire_bytes_ring_formulas():
    wb = asharding.collective_wire_bytes
    # all_reduce: 2(n-1)/n x payload
    assert wb(_cop("all_reduce", 1024)) == 2 * 4096 * 3 // 4
    # all_gather: result is the full buffer -> (n-1)/n x result
    assert wb(_cop("all_gather", 1024)) == 4096 * 3 // 4
    # native reduce_scatter: result is the shard -> (n-1) x result
    assert wb(_cop("reduce_scatter", 256)) == 1024 * 3
    # decomposed RS records the FULL all-reduce payload
    assert wb(_cop("reduce_scatter", 1024, decomposed=True)) == \
        4096 * 3 // 4
    # permute: one hop, whole payload
    assert wb(_cop("collective_permute", 1024)) == 4096
    # single-participant groups move nothing
    assert wb(_cop("all_gather", 1024, group=1)) == 0


def test_comm_cost_per_axis():
    census = CollectiveStats(ops=[
        _cop("all_reduce", 1024, axes=("dp",)),
        _cop("collective_permute", 512, axes=("pp",)),
        _cop("all_gather", 2048, axes=()),
    ])
    prof = asharding.BandwidthProfile(10.0, {"pp": 1.0}, name="test")
    cost = asharding.comm_cost(census, profile=prof)
    assert set(cost.per_axis_s) == {"dp", "pp", "?"}
    # permute: 2048 B over 1 GB/s
    assert cost.per_axis_s["pp"] == pytest.approx(2048 / 1e9)
    assert cost.per_axis_bytes["dp"] == 2 * 4096 * 3 // 4
    assert cost.total_bytes == sum(cost.per_axis_bytes.values())
    assert cost.total_s == pytest.approx(sum(cost.per_axis_s.values()))
    # ranked per-op table
    assert cost.per_op[0]["seconds"] >= cost.per_op[-1]["seconds"]


def test_bandwidth_profile_parsing(monkeypatch):
    p = asharding.BandwidthProfile.parse("dcn")
    assert p.default_gbps == asharding.DCN_BANDWIDTH_GBPS
    p = asharding.BandwidthProfile.parse("42.5")
    assert p.default_gbps == 42.5
    p = asharding.BandwidthProfile.parse("dp=ici,pp=dcn,default=7")
    assert p.gbps(("dp",)) == asharding.ICI_BANDWIDTH_GBPS
    assert p.gbps(("pp",)) == asharding.DCN_BANDWIDTH_GBPS
    assert p.gbps(("ep",)) == 7.0
    monkeypatch.setenv("MXNET_SHARDING_BANDWIDTH", "dp=3")
    env = asharding.bandwidth_profile()
    assert env.gbps(("dp",)) == 3.0
    monkeypatch.delenv("MXNET_SHARDING_BANDWIDTH")
    assert asharding.bandwidth_profile().default_gbps == \
        asharding.CPU_BANDWIDTH_GBPS      # cpu backend default


# ---------------------------------------------------------------------------
# implicit-reshard detection
# ---------------------------------------------------------------------------

@needs_mesh
def test_planted_mismatched_spec_yields_ranked_reshard():
    """The acceptance case: a P('dp', None) input whose output layout
    forces the partitioner to gather it to replicated — the audit must
    produce a ranked implicit-reshard finding with the gather's correct
    byte count and the producing op named."""
    mesh = make_mesh({"dp": DP}, jax.devices()[:DP])
    xs = NamedSharding(mesh.mesh, P("dp", None))
    rs = NamedSharding(mesh.mesh, P())
    x = jax.device_put(jnp.ones((64, 128), jnp.float32), xs)
    w = jax.device_put(jnp.ones((128, 32), jnp.float32), rs)
    lowered = jax.jit(lambda a, b: jnp.tanh(a @ b),
                      in_shardings=(xs, rs),
                      out_shardings=rs).lower(x, w)
    report = analyze_lowered(lowered, mesh=mesh)
    pack = asharding.SpecPack(name="pure-dp",
                              description="dp batch-sharded forward")
    findings = asharding.expect_spec(report, pack)
    reshards = report.sharding.reshards
    assert len(reshards) == 1
    r = reshards[0]
    assert r.kind == "all_gather"
    # the gathered output is the full (64, 32) f32 buffer
    assert r.payload_bytes == 64 * 32 * 4
    assert r.wire_bytes == 64 * 32 * 4 * (DP - 1) // DP
    assert r.producer                       # producing op is named
    # budget 0 -> an error-severity finding fails analyze='raise'
    errs = [f for f in findings
            if f.rule == "implicit-reshard" and f.severity == "error"]
    assert errs and str(r.payload_bytes) in errs[0].message
    assert not report.ok


def test_declared_rules_bless_reshards():
    census = CollectiveStats(ops=[
        _cop("all_gather", 4096, name="ag.weights"),
        _cop("all_to_all", 4096, name="a2a.stray"),
    ])
    blessed = asharding.implicit_reshards(
        census,
        declared=[asharding.CollectiveRule(
            "all_gather", elements=frozenset([4096]))])
    assert [r.name for r in blessed] == ["a2a.stray"]
    # below the floor nothing fires
    assert asharding.implicit_reshards(
        CollectiveStats(ops=[_cop("all_gather", 64)])) == []
    # ranked by wire bytes
    ranked = asharding.implicit_reshards(CollectiveStats(ops=[
        _cop("all_gather", 2048, name="small"),
        _cop("all_gather", 65536, name="big")]))
    assert [r.name for r in ranked] == ["big", "small"]


# ---------------------------------------------------------------------------
# expect_spec packs
# ---------------------------------------------------------------------------

def _zero_setup(hidden=(8, 5, 3), bs=8, seed=3):
    """The canonical zero-sharded MLP of tests/test_zero_shard.py."""
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden[0], in_units=4, activation="relu"))
    net.add(nn.Dense(hidden[1], in_units=hidden[0], activation="relu"))
    net.add(nn.Dense(hidden[2], in_units=hidden[1]))
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(bs, 4).astype("float32"))
    y = nd.array(rng.randint(0, 3, size=(bs,)).astype("int32"))
    return step, x, y


@pytest.fixture(scope="module")
def zero_report():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    step, x, y = _zero_setup()
    with make_mesh({"dp": DP}, jax.devices()[:DP]) as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        step(xs, ys)
        return step.analyze(xs, ys)


@needs_mesh
def test_zero_pack_passes_on_real_program(zero_report):
    """The zero-dp spec pack over the real ZeRO step: collective
    signature present, ZERO implicit reshards above the floor, state
    shards at ~1/dp — and the audit riding the ProgramReport."""
    rep = zero_report
    assert rep.ok, rep.summary()
    audit = rep.sharding
    assert audit is not None and audit.pack == "zero-dp"
    assert audit.reshards == []
    assert audit.brief()["implicit_reshards"] == 0
    # the momentum shard is in the table, P(dp), at exactly 1/dp
    shards = [r for r in audit.table.params
              if r.sharding is not None
              and r.sharding.spec == ("dp",) and "sts" in r.name]
    assert shards, audit.table.table_str()
    for s in shards:
        assert s.bytes_global == s.bytes_local * DP
    # the batch input resolved as P(dp, -)
    batch = [r for r in audit.table.params
             if r.sharding is not None
             and r.sharding.spec == ("dp", None)]
    assert batch
    # comm cost attributed entirely to the dp axis
    assert set(audit.cost.per_axis_s) == {"dp"}
    assert audit.cost.total_s > 0


@needs_mesh
def test_tp_attention_pack():
    """Megatron-split attention (column-sharded QKV, row-sharded output
    proj): exactly the one output all-reduce on tp, zero reshards."""
    from mxnet_tpu.ops.attention import flash_attention
    mesh = make_mesh({"tp": DP}, jax.devices()[:DP])
    B, S, D, HD = 4, 16, 64, 8

    def tp_attn(x, wq, wk, wv, wo):
        def split(t):
            return t.reshape(B, S, D // HD, HD).transpose(0, 2, 1, 3)
        q, k, v = split(x @ wq), split(x @ wk), split(x @ wv)
        o = flash_attention(q, k, v)
        return o.transpose(0, 2, 1, 3).reshape(B, S, D) @ wo

    col = NamedSharding(mesh.mesh, P(None, "tp"))
    row = NamedSharding(mesh.mesh, P("tp", None))
    rep0 = NamedSharding(mesh.mesh, P())
    x = jnp.ones((B, S, D), jnp.float32)
    w = jnp.ones((D, D), jnp.float32) * 0.02
    lowered = jax.jit(tp_attn,
                      in_shardings=(rep0, col, col, col, row)) \
        .lower(x, w, w, w, w)
    report = analyze_lowered(lowered, mesh=mesh)
    findings = asharding.expect_spec(report, "tp-attention")
    assert findings == [], [str(f) for f in findings]
    assert report.collectives.count("all_reduce", axis="tp") == 1
    assert report.sharding.reshards == []
    # the tp-sharded projection weights sit at 1/tp per device
    loc, glob = report.sharding.table.sharded_bytes("tp")
    assert glob == loc * DP


@needs_mesh
def test_ring_attention_pack():
    """Sequence-parallel ring attention: K/V ppermute ring hops on sp,
    nothing gathered."""
    from mxnet_tpu.ops.attention import ring_attention_sharded
    mesh = make_mesh({"sp": DP}, jax.devices()[:DP])
    q = jnp.ones((2, 2, 32, 8), jnp.float32)
    lowered = jax.jit(
        lambda a, b, c: ring_attention_sharded(a, b, c, mesh,
                                               axis="sp")) \
        .lower(q, q, q)
    report = analyze_lowered(lowered, mesh=mesh)
    findings = asharding.expect_spec(report, "sp-ring-attention")
    assert findings == [], [str(f) for f in findings]
    assert report.collectives.count("collective_permute",
                                    axis="sp") >= 2
    assert report.sharding.reshards == []
    baselines = asharding.load_baselines(BASELINES)
    assert asharding.check_baseline(report.sharding, baselines,
                                    "sp-ring-attention") == []


def test_pack_violation_fires_spec_mismatch():
    """A census without the pack's required collective yields an
    error-severity finding naming the pack."""
    census = CollectiveStats(ops=[_cop("all_reduce", 128)])
    pack = asharding.SpecPack(
        name="wants-rs", description="test",
        rules=(asharding.CollectiveRule("reduce_scatter", axis="dp",
                                        min_count=1),))
    findings = asharding.expect_spec(census, pack)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "spec-mismatch" and f.severity == "error"
    assert "wants-rs" in f.message


def test_pack_max_count_and_wildcard():
    census = CollectiveStats(ops=[_cop("all_gather", 128),
                                  _cop("all_reduce", 128)])
    pack = asharding.SpecPack(
        name="none-allowed", description="test",
        rules=(asharding.CollectiveRule("*", max_count=0,
                                        severity="warn"),))
    findings = asharding.expect_spec(census, pack)
    assert len(findings) == 1 and findings[0].severity == "warn"


def test_state_budget_violation():
    """A pack with a state axis over a table whose 'sharded' buffers
    secretly hold full copies must fire the state-budget finding."""
    repl = asharding.OpSharding(kind="tiled", tile_dims=(4,),
                                device_order=(0, 1, 2, 3))
    repl.spec = ("dp",)
    table = asharding.ShardingTable(params=[asharding.ParamSharding(
        index=0, name="sts[0]", role="parameter",
        local_shape=(1024,), global_shape=(1024,), dtype="f32",
        bytes_local=4096, bytes_global=4096, sharding=repl)])

    class _Rep:
        collectives = CollectiveStats()
        sharding = asharding.ShardingAudit(table=table)
        findings = []

        def add(self, f):
            self.findings.append(f)

    mesh = make_mesh({"dp": min(4, len(jax.devices()))},
                     jax.devices()[:min(4, len(jax.devices()))]) \
        if len(jax.devices()) >= 4 else None
    if mesh is None:
        pytest.skip("needs >=4 devices")
    pack = asharding.SpecPack(name="budget", description="test",
                              state_axis="dp")
    rep = _Rep()
    findings = asharding.expect_spec(rep, pack, mesh=mesh)
    assert any(f.rule == "state-budget" for f in findings)


def test_mode_pack_zero_keeps_historical_rules():
    """The declarative zero pack preserves expect_mode's historical
    finding vocabulary (the tier-1 fixtures assert these rule ids)."""
    pack = mode_spec_pack("zero", axis="dp", unit_sizes=[1024])
    ids = {r.rule_id for r in pack.rules}
    assert ids == {"collective-mismatch", "per-param-allreduce"}
    assert pack.max_reshard_bytes is None
    assert mode_spec_pack("fused").rules[0].severity == "warn"
    assert mode_spec_pack("predict") is not None
    assert mode_spec_pack("split") is None


# ---------------------------------------------------------------------------
# baseline regression gate
# ---------------------------------------------------------------------------

def _audit(n_reshards=0, bytes_each=8192):
    a = asharding.ShardingAudit()
    for i in range(n_reshards):
        a.reshards.append(asharding.Reshard(
            name=f"ag.{i}", kind="all_gather", axes=("dp",),
            group_size=4, elements=bytes_each // 4, dtype="f32",
            payload_bytes=bytes_each,
            wire_bytes=bytes_each * 3 // 4, seconds=1e-6))
    return a


def test_check_baseline_pass_and_regress():
    baselines = {"leg": {"implicit_reshards": 1,
                         "reshard_bytes": 8192, "tol_pct": 25}}
    assert asharding.check_baseline(_audit(1), baselines, "leg") == []
    worse = asharding.check_baseline(_audit(3), baselines, "leg")
    assert [f.rule for f in worse] == ["sharding-regression"] * 2
    assert all(f.severity == "error" for f in worse)
    missing = asharding.check_baseline(_audit(0), baselines, "nope")
    assert missing[0].severity == "warn"


def test_baseline_from_env(monkeypatch, tmp_path):
    monkeypatch.delenv("MXNET_SHARDING_BASELINE", raising=False)
    assert asharding.baseline_from_env() is None
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"zero": {"implicit_reshards": 0}}))
    monkeypatch.setenv("MXNET_SHARDING_BASELINE", str(p))
    got = asharding.baseline_from_env()
    assert got == ({"zero": {"implicit_reshards": 0}}, None)
    monkeypatch.setenv("MXNET_SHARDING_BASELINE", f"{p}:zero")
    assert asharding.baseline_from_env()[1] == "zero"
    monkeypatch.setenv("MXNET_SHARDING_BASELINE", "/nope/missing.json")
    assert asharding.baseline_from_env() is None


@needs_mesh
def test_analyze_raise_fails_fast_on_injected_regression(monkeypatch,
                                                         tmp_path):
    """The acceptance case: MXNET_SHARDING_BASELINE + analyze='raise'
    must fail the FIRST step when the program's reshard posture exceeds
    the armed baseline.  Injection mirrors the fusion gate's tight.json
    approach — a baseline demanding strictly fewer reshards than the
    program has (the partitioner chooses its gather-vs-psum strategy by
    size, so a model-shape injection would pin XLA internals instead of
    the gate)."""
    p = tmp_path / "tight.json"
    p.write_text(json.dumps(
        {"zero": {"implicit_reshards": -1, "reshard_bytes": -1,
                  "tol_pct": 0}}))
    monkeypatch.setenv("MXNET_SHARDING_BASELINE", f"{p}:zero")
    step, x, y = _zero_setup(seed=11)
    step._analyze = "raise"
    with make_mesh({"dp": DP}, jax.devices()[:DP]) as mesh:
        xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
        with pytest.raises(MXNetError, match="sharding-regression"):
            step(xs, ys)


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

@needs_mesh
def test_sharding_gauges_published(zero_report):
    names = telemetry.names
    reg = telemetry.registry()
    g = reg.get(names.SHARDING_RESHARDS)
    assert g is not None and g.values()[""] == 0.0
    cost = reg.get(names.SHARDING_COMM_COST)
    assert cost is not None and cost.values().get("dp", 0) > 0
    b = reg.get(names.SHARDING_COLLECTIVE_BYTES)
    assert b is not None and b.values().get("dp", 0) > 0


# ---------------------------------------------------------------------------
# SPMD fusion-census accounting (satellite)
# ---------------------------------------------------------------------------

@needs_mesh
def test_fusion_census_stays_per_shard_at_dp4(zero_report):
    """The dp=4 census pin: the partitioned module's shapes are already
    per-shard, so the census FLOP total of the dp=4 program must come
    in well BELOW the dp=1 program of the same logical model — global
    logical shapes would put it at >= the dp=1 total."""
    step1, x, y = _zero_setup(seed=3)
    step1(x, y)
    rep1 = step1.analyze(x, y)
    f1 = rep1.fusion.total_flops
    f4 = zero_report.fusion.total_flops
    assert f4 < f1, (f4, f1)


def test_fusion_census_divides_global_shape_sharded_module():
    """An UNpartitioned num_partitions=4 module (no _spmd entry) still
    carries global shapes + sharding annotations: the census must
    divide annotated ops' FLOPs/bytes by their tile factor."""
    tmpl = textwrap.dedent("""\
    HloModule jit_g, is_scheduled=true, entry_computation_layout={(f32[64,64]{1,0})->f32[64,64]{1,0}}, num_partitions=4

    ENTRY %main.9 (p0: f32[64,64]) -> f32[64,64] {
      %p0 = f32[64,64]{1,0} parameter(0)
      ROOT %dot.1 = f32[64,64]{1,0} dot(f32[64,64]{1,0} %p0, f32[64,64]{1,0} %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}%SHARD%
    }
    """)
    plain = fusion_census(tmpl.replace("%SHARD%", ""))
    sharded = fusion_census(tmpl.replace(
        "%SHARD%", ", sharding={devices=[4,1]<=[4]}"))
    [k0] = plain.kernels
    [k4] = sharded.kernels
    assert k4.flops == k0.flops // 4
    assert k4.bytes_out == k0.bytes_out // 4
    # a PARTITIONED module (entry *_spmd) is never rescaled
    part = fusion_census(_CANNED_SPMD)
    dot = [k for k in part.kernels if k.kind == "dot"][0]
    assert dot.flops == 2 * 2 * 8 * 16        # the per-shard dot as-is


# ---------------------------------------------------------------------------
# MXA006 source lint (satellite)
# ---------------------------------------------------------------------------

_MXA006_SRC = textwrap.dedent("""\
class Net:
    def forward(self, x):
        import jax
        from jax import lax
        a = jax.device_put(x)
        b = place_on_mesh(x)
        c = lax.psum(x, "dp")
        d = jax.device_put(x, some_sharding)
        e = place_on_mesh(mesh, "dp", x)
        f = lax.all_gather(x, "dp")  # mx-lint: allow=MXA006
        return a + b + c + d + e + f
""")


def test_mxa006_rules():
    findings = [f for f in lint_source(_MXA006_SRC, "pkg/net.py")
                if f.rule == "MXA006"]
    by_line = {int(f.where.rsplit(":", 1)[1]): f for f in findings}
    assert set(by_line) == {5, 6, 7, 10}
    assert by_line[5].severity == "error"      # bare device_put
    assert by_line[6].severity == "error"      # bare place_on_mesh
    assert by_line[7].severity == "warn"       # raw lax collective
    assert by_line[10].blessed                 # inline blessing
    # explicit sharding / mesh+axis forms (lines 8-9) are clean
    assert 8 not in by_line and 9 not in by_line


def test_mxa006_exempts_collectives_home():
    findings = lint_source(_MXA006_SRC,
                           "mxnet_tpu/parallel/collectives.py")
    raw = [f for f in findings if f.rule == "MXA006"
           and "lax." in f.message]
    assert raw == []


# ---------------------------------------------------------------------------
# tier-1 baseline sweep (lint-marked, like the fusion gate)
# ---------------------------------------------------------------------------

@pytest.mark.lint
@needs_mesh
def test_sharding_baseline_sweep(zero_report):
    """The checked-in reshard posture of the canonical zero-sharded
    MLP: every collective implied by the spec pack, zero implicit
    reshards — enforced against tests/fixtures/sharding_baselines.json
    on every tier-1 run."""
    baselines = asharding.load_baselines(BASELINES)
    findings = asharding.check_baseline(zero_report.sharding,
                                        baselines, "zero")
    assert findings == [], [str(f) for f in findings]
