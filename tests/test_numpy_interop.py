"""NumPy interop protocols on mx.np.ndarray.

Reference analogs: numpy_dispatch_protocol.py (+ its sanity test
pattern in tests/python/unittest/test_numpy_interoperability.py),
numpy/fallback.py, and the 3 multiarray tail names
(triu_indices/triu_indices_from/unravel_index,
reference numpy/multiarray.py:5902,7876).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.numpy as np
from mxnet_tpu import autograd


def test_array_function_dispatches_to_mx():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    m = onp.mean(a)
    assert isinstance(m, np.ndarray)
    assert float(m.asnumpy()) == pytest.approx(2.5)
    s = onp.concatenate([a, a], axis=0)
    assert isinstance(s, np.ndarray) and s.shape == (4, 2)
    t = onp.transpose(a)
    assert isinstance(t, np.ndarray)
    onp.testing.assert_allclose(t.asnumpy(), a.asnumpy().T)


def test_array_ufunc_mixed_operands():
    """Casting table (reference multiarray.py:314): `c = a + b` with one
    official-numpy operand and one mx operand yields mx."""
    a = np.array([1.0, 2.0])
    b = onp.array([10.0, 20.0])
    for r in (onp.add(b, a), onp.add(a, b), a + b, b + a):
        assert isinstance(r, np.ndarray)
        onp.testing.assert_allclose(r.asnumpy(), [11.0, 22.0])
    r = onp.multiply(b, a)
    assert isinstance(r, np.ndarray)
    onp.testing.assert_allclose(r.asnumpy(), [10.0, 40.0])


def test_ufunc_dispatch_stays_on_device_path():
    """Dispatched ufuncs must run the mx implementation (and therefore
    be autograd-recordable), not a host fallback."""
    a = np.array([1.0, 2.0])
    a.attach_grad()
    with autograd.record():
        y = onp.multiply(a, a).sum()
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), [2.0, 4.0])


def test_fallback_operator_path():
    a = np.array([3.0, 1.0, 2.0, 4.0])
    w = onp.argpartition(a, 2)        # no native mx impl -> fallback
    assert isinstance(w, np.ndarray)
    assert sorted(int(i) for i in w.asnumpy()) == [0, 1, 2, 3]
    # fallback namespace is also importable directly, reference-style
    r = np.intersect1d(np.array([1, 2, 3]), np.array([2, 3, 4]))
    assert isinstance(r, np.ndarray)
    assert r.asnumpy().tolist() == [2, 3]


def test_ufunc_host_out_buffer_is_written():
    """onp.add(mx, mx, out=host_buf) must fill the host buffer (NumPy's
    out= contract; review finding round 4)."""
    a = np.array([1.0, 2.0])
    buf = onp.empty(2, dtype="float32")
    r = onp.add(a, a, out=buf)
    assert r is buf
    onp.testing.assert_allclose(buf, [2.0, 4.0])


def test_ufunc_methods_fall_back_to_host():
    """onp.add.reduce / onp.multiply.outer on mx arrays worked via
    __array__ coercion before the protocol landed; they must keep
    working (review finding round 4)."""
    a = np.array([1.0, 2.0, 3.0])
    r = onp.add.reduce(a)
    assert float(r.asnumpy() if hasattr(r, "asnumpy") else r) == 6.0
    o = onp.multiply.outer(a, a)
    got = o.asnumpy() if hasattr(o, "asnumpy") else o
    onp.testing.assert_allclose(got, onp.multiply.outer(
        a.asnumpy(), a.asnumpy()))


def test_fallback_refused_under_recording():
    a = np.array([3.0, 1.0, 2.0])
    a.attach_grad()
    with autograd.record():
        with pytest.raises(mx.MXNetError, match="fallback"):
            np.argpartition(a, 1)


def test_fallback_list_sanity():
    """Reference test pattern: every catalogued fallback name must be
    resolvable in mx.np, unless this numpy build dropped it."""
    from mxnet_tpu.numpy import fallback
    dup = [n for n in fallback.__all__
           if fallback.__all__.count(n) > 1]
    assert not dup
    for name in fallback.__all__:
        if hasattr(onp, name):
            assert hasattr(np, name), f"missing fallback install: {name}"
        else:
            assert not hasattr(np, name) or name in ("divmod",), name


def test_fallback_does_not_shadow_native():
    """Native mx.np impls keep priority over the fallback installer."""
    assert not getattr(np.mean, "_is_np_fallback", False)
    assert not getattr(np.unwrap, "_is_np_fallback", False)
    assert not getattr(np.signbit, "_is_np_fallback", False)


def test_triu_indices_and_from():
    iu1 = np.triu_indices(4)
    a = np.arange(16).reshape(4, 4)
    vals = a.asnumpy()[tuple(i.asnumpy() for i in iu1)]
    ref = onp.arange(16).reshape(4, 4)
    onp.testing.assert_array_equal(vals,
                                   ref[onp.triu_indices(4)])
    iu2 = np.triu_indices_from(a, k=2)
    onp.testing.assert_array_equal(
        onp.stack([i.asnumpy() for i in iu2]),
        onp.stack(onp.triu_indices_from(ref, k=2)))
    il = np.tril_indices_from(a)
    onp.testing.assert_array_equal(
        onp.stack([i.asnumpy() for i in il]),
        onp.stack(onp.tril_indices_from(ref)))


def test_unravel_index():
    out = np.unravel_index(np.array([22, 41, 37], dtype="int32"), (7, 6))
    assert isinstance(out, np.ndarray)
    onp.testing.assert_array_equal(out.asnumpy(),
                                   [[3, 6, 6], [4, 5, 1]])
    scalar = np.unravel_index(1621, (6, 7, 8, 9))
    onp.testing.assert_array_equal(scalar.asnumpy(), [3, 1, 4, 1])
    with pytest.raises(mx.MXNetError):
        np.unravel_index(5, (3, 3), order="F")


def test_ufunc_unsupported_kwarg_falls_back_to_host():
    """where= is a legal ufunc option (util.np_ufunc_legal_option) that
    the mx implementations don't take; the protocol must fall back to
    host instead of raising TypeError (advisor round-4 low)."""
    a = np.array([1.0, 2.0, 3.0])
    got = onp.add(a, a, where=onp.array([True, False, True]))
    assert isinstance(got, np.ndarray)
    vals = got.asnumpy()
    assert vals[0] == 2.0 and vals[2] == 6.0


def test_ufunc_unsupported_kwarg_refused_under_recording():
    a = np.array([1.0, 2.0, 3.0])
    a.attach_grad()
    with pytest.raises(mx.MXNetError):
        with autograd.record():
            onp.add(a, a, where=onp.array([True, False, True]))
