"""Driver-artifact robustness: the dryrun's first-contact watchdog.

Round 3 lost the MULTICHIP artifact (rc=124) because
``dryrun_multichip`` touched ``jax.devices()`` on a wedged accelerator
tunnel before deciding to re-exec on the virtual CPU mesh. These tests
pin the fix: the probe times out in a daemon thread and reports None so
the caller falls through to the tunnel-independent virtual-mesh path
(reference analog: the driver-facing robustness the reference gets from
its engine shutdown watchdogs, src/engine/threaded_engine_perdevice.cc).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft


def test_probe_devices_returns_devices_on_healthy_platform():
    devs = graft._probe_devices(timeout=60)
    assert devs is not None and len(devs) >= 1


def test_probe_devices_times_out_on_hung_platform(monkeypatch):
    import jax

    def hung(*a, **k):
        time.sleep(300)

    monkeypatch.setattr(jax, "devices", hung)
    t0 = time.time()
    assert graft._probe_devices(timeout=1.0) is None
    assert time.time() - t0 < 30  # returned promptly, didn't block on hang


def test_probe_devices_reports_error_as_none(monkeypatch):
    import jax

    def broken(*a, **k):
        raise RuntimeError("tunnel reset")

    monkeypatch.setattr(jax, "devices", broken)
    assert graft._probe_devices(timeout=10) is None


def test_probe_child_mode_is_authoritative(monkeypatch):
    # the virtual-mesh child must NOT thread/timeout: its result gates the
    # recursion-abort check in _reexec_dryrun_on_virtual_mesh
    monkeypatch.setenv("MXNET_DRYRUN_CHILD", "1")
    devs = graft._probe_devices()
    assert devs is not None and len(devs) >= 1
