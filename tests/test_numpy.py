"""mx.np / mx.npx frontend tests.

Mirrors the reference's tests/python/unittest/test_numpy_op.py /
test_numpy_ndarray.py strategy: golden values vs real NumPy plus autograd
checks through the np frontend.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_array_creation():
    a = np.array([[1, 2], [3, 4]])
    assert isinstance(a, np.ndarray)
    assert a.shape == (2, 2)
    assert a.dtype == onp.float32
    assert np.zeros((2, 3)).asnumpy().sum() == 0
    assert np.ones(4).asnumpy().sum() == 4
    assert np.full((2,), 7.0).asnumpy().tolist() == [7.0, 7.0]
    assert np.arange(5).asnumpy().tolist() == [0, 1, 2, 3, 4]
    assert np.eye(3).asnumpy().trace() == 3.0
    assert np.linspace(0, 1, 5).shape == (5,)
    assert np.zeros_like(a).shape == (2, 2)


def test_ufuncs_match_numpy():
    x = onp.random.uniform(0.1, 2.0, size=(3, 4)).astype(onp.float32)
    mxx = np.array(x)
    for name in ["exp", "log", "sqrt", "sin", "cos", "tanh", "floor",
                 "ceil", "square", "sign", "log1p", "expm1", "arctan"]:
        assert_almost_equal(getattr(np, name)(mxx), getattr(onp, name)(x),
                            rtol=1e-5, atol=1e-5, names=(name, "numpy"))


def test_binary_broadcast_and_scalars():
    a = onp.random.uniform(-1, 1, (2, 3)).astype(onp.float32)
    b = onp.random.uniform(0.5, 1.5, (3,)).astype(onp.float32)
    ma, mb = np.array(a), np.array(b)
    assert_almost_equal(ma + mb, a + b)
    assert_almost_equal(ma * mb, a * b)
    assert_almost_equal(ma / mb, a / b)
    assert_almost_equal(ma ** 2, a ** 2)
    assert_almost_equal(2 - ma, 2 - a)
    assert_almost_equal(np.maximum(ma, 0.0), onp.maximum(a, 0))
    assert ((ma > 0).asnumpy() == (a > 0)).all()


def test_reductions():
    x = onp.random.uniform(-1, 1, (4, 5)).astype(onp.float32)
    mxx = np.array(x)
    assert_almost_equal(np.sum(mxx), onp.sum(x), rtol=1e-4)
    assert_almost_equal(np.mean(mxx, axis=0), onp.mean(x, axis=0))
    assert_almost_equal(np.var(mxx, axis=1), onp.var(x, axis=1), rtol=1e-4,
                        atol=1e-5)
    assert_almost_equal(np.std(mxx), onp.std(x), rtol=1e-4, atol=1e-5)
    assert_almost_equal(mxx.max(axis=1), x.max(axis=1))
    assert int(np.argmax(mxx)) == int(onp.argmax(x))
    assert_almost_equal(np.cumsum(mxx, axis=0), onp.cumsum(x, axis=0),
                        rtol=1e-4, atol=1e-5)


def test_manipulation():
    x = onp.arange(24, dtype=onp.float32).reshape(2, 3, 4)
    mxx = np.array(x)
    assert np.transpose(mxx).shape == (4, 3, 2)
    assert np.swapaxes(mxx, 0, 2).shape == (4, 3, 2)
    assert np.moveaxis(mxx, 0, -1).shape == (3, 4, 2)
    assert np.expand_dims(mxx, 1).shape == (2, 1, 3, 4)
    assert np.squeeze(np.expand_dims(mxx, 0)).shape == (2, 3, 4)
    assert np.reshape(mxx, (6, 4)).shape == (6, 4)
    assert np.concatenate([mxx, mxx], axis=2).shape == (2, 3, 8)
    assert np.stack([mxx, mxx]).shape == (2, 2, 3, 4)
    parts = np.split(mxx, 2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (1, 3, 4)
    assert_almost_equal(np.flip(mxx, 0), onp.flip(x, 0))
    assert_almost_equal(np.roll(mxx, 1, axis=1), onp.roll(x, 1, axis=1))
    assert np.tile(mxx, (2, 1, 1)).shape == (4, 3, 4)
    assert np.repeat(mxx, 2, axis=1).shape == (2, 6, 4)
    assert_almost_equal(np.where(mxx > 10, mxx, 0.0),
                        onp.where(x > 10, x, 0))
    assert_almost_equal(np.clip(mxx, 2, 10), onp.clip(x, 2, 10))


def test_linalg():
    a = onp.random.uniform(-1, 1, (4, 4)).astype(onp.float32)
    spd = a @ a.T + 4 * onp.eye(4, dtype=onp.float32)
    msp = np.array(spd)
    assert_almost_equal(np.linalg.inv(msp) @ msp, onp.eye(4), rtol=1e-2,
                        atol=1e-3)
    L = np.linalg.cholesky(msp)
    assert_almost_equal(L @ L.T, spd, rtol=1e-3, atol=1e-3)
    w, v = np.linalg.eigh(msp)
    assert (onp.diff(w.asnumpy()) >= -1e-4).all()
    q, r = np.linalg.qr(np.array(a))
    assert_almost_equal(q @ r, a, rtol=1e-3, atol=1e-4)
    u, s, vt = np.linalg.svd(np.array(a))
    assert_almost_equal((u * s) @ vt, a, rtol=1e-3, atol=1e-4)
    b = onp.random.uniform(-1, 1, (4,)).astype(onp.float32)
    xs = np.linalg.solve(msp, np.array(b))
    assert_almost_equal(msp @ xs, b, rtol=1e-3, atol=1e-3)
    assert_almost_equal(np.linalg.norm(np.array(a)), onp.linalg.norm(a),
                        rtol=1e-4)
    assert_almost_equal(np.linalg.det(msp), onp.linalg.det(spd), rtol=1e-2)


def test_np_random():
    np.random.seed(42)
    u = np.random.uniform(0, 1, size=(1000,))
    assert 0.4 < float(u.asnumpy().mean()) < 0.6
    n = np.random.normal(2.0, 0.5, size=(1000,))
    assert 1.8 < float(n.asnumpy().mean()) < 2.2
    r = np.random.randint(0, 10, size=(100,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10
    g = np.random.gamma(3.0, 2.0, size=(2000,))
    assert 5.0 < float(g.asnumpy().mean()) < 7.0
    # reproducibility
    np.random.seed(7)
    a = np.random.uniform(size=(5,)).asnumpy()
    np.random.seed(7)
    b = np.random.uniform(size=(5,)).asnumpy()
    assert (a == b).all()


def test_np_autograd():
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with mx.autograd.record():
        y = np.sum(x * x) + np.mean(x)
    y.backward()
    assert isinstance(x.grad, np.ndarray)
    assert_almost_equal(x.grad, 2 * x.asnumpy() + 0.25)


def test_np_autograd_matmul_chain():
    a = np.array(onp.random.uniform(-1, 1, (3, 4)).astype(onp.float32))
    b = np.array(onp.random.uniform(-1, 1, (4, 2)).astype(onp.float32))
    a.attach_grad()
    b.attach_grad()
    with mx.autograd.record():
        out = np.sum(np.tanh(a @ b))
    out.backward()
    assert a.grad.shape == (3, 4) and b.grad.shape == (4, 2)
    check_numeric_gradient(lambda p, q: np.tanh(p @ q), [a, b])


def test_npx_ops():
    x = np.array([[-1.0, 2.0, -3.0]])
    assert_almost_equal(npx.relu(x), [[0.0, 2.0, 0.0]])
    assert_almost_equal(npx.sigmoid(np.array([0.0])), [0.5])
    s = npx.softmax(np.array([[1.0, 2.0, 3.0]]))
    assert_almost_equal(np.sum(s), 1.0, rtol=1e-5)
    oh = npx.one_hot(np.array([0, 2], dtype='int32'), 3)
    assert oh.asnumpy().tolist() == [[1, 0, 0], [0, 0, 1]]
    e = npx.erf(np.array([0.0, 1e8]))
    assert_almost_equal(e, [0.0, 1.0])
    m = npx.masked_softmax(np.array([[1.0, 2.0, 3.0]]),
                           np.array([[1, 1, 0]]))
    assert abs(float(np.sum(m)) - 1.0) < 1e-5
    assert float(m[0, 2]) == 0.0


def test_np_nd_interop():
    a = mx.nd.array([1.0, 2.0])
    b = a.as_np_ndarray()
    assert isinstance(b, np.ndarray)
    c = b.as_nd_ndarray()
    assert type(c).__name__ == "NDArray"
    assert_almost_equal(b + 1, [2.0, 3.0])


def test_einsum_tensordot_grad():
    a = np.array(onp.random.uniform(-1, 1, (2, 3)).astype(onp.float32))
    b = np.array(onp.random.uniform(-1, 1, (3, 4)).astype(onp.float32))
    a.attach_grad()
    with mx.autograd.record():
        y = np.sum(np.einsum("ij,jk->ik", a, b))
    y.backward()
    assert_almost_equal(a.grad, onp.broadcast_to(
        b.asnumpy().sum(axis=1), (2, 3)))
    td = np.tensordot(a, b, axes=1)
    assert td.shape == (2, 4)


def test_sort_take_unique():
    x = np.array([3.0, 1.0, 2.0, 1.0])
    assert np.sort(x).asnumpy().tolist() == [1.0, 1.0, 2.0, 3.0]
    assert np.argsort(x).asnumpy().tolist() == [1, 3, 2, 0]
    u = np.unique(x)
    assert u.asnumpy().tolist() == [1.0, 2.0, 3.0]
    t = np.take(x, np.array([0, 3], dtype='int32'))
    assert t.asnumpy().tolist() == [3.0, 1.0]


def test_fft():
    x = onp.random.uniform(-1, 1, (8,)).astype(onp.float32)
    got = np.fft.fft(np.array(x)).asnumpy()
    want = onp.fft.fft(x)
    assert onp.allclose(got, want, rtol=1e-4, atol=1e-4)
