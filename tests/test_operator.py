"""Broad operator numerics (reference: tests/python/unittest/
test_operator.py, 9.3k LoC — golden values vs NumPy + finite-difference
gradient checks via check_numeric_gradient).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient

RNG = onp.random.RandomState(42)


def _a(shape, lo=-2.0, hi=2.0):
    return RNG.uniform(lo, hi, shape).astype("float32")


UNARY_CASES = [
    ("relu", lambda x: nd.relu(x), lambda x: onp.maximum(x, 0), (-2, 2)),
    ("sigmoid", lambda x: nd.sigmoid(x),
     lambda x: 1 / (1 + onp.exp(-x)), (-3, 3)),
    ("tanh", lambda x: nd.tanh(x), onp.tanh, (-2, 2)),
    ("exp", lambda x: nd.exp(x), onp.exp, (-2, 2)),
    ("log", lambda x: nd.log(x), onp.log, (0.1, 4)),
    ("sqrt", lambda x: nd.sqrt(x), onp.sqrt, (0.1, 4)),
    ("rsqrt", lambda x: nd.rsqrt(x), lambda x: 1 / onp.sqrt(x), (0.1, 4)),
    ("abs", lambda x: nd.abs(x), onp.abs, (-2, 2)),
    ("square", lambda x: nd.square(x), onp.square, (-2, 2)),
    ("cbrt", lambda x: nd.cbrt(x), onp.cbrt, (-2, 2)),
    ("sin", lambda x: nd.sin(x), onp.sin, (-3, 3)),
    ("cos", lambda x: nd.cos(x), onp.cos, (-3, 3)),
    ("arctan", lambda x: nd.arctan(x), onp.arctan, (-2, 2)),
    ("erf", lambda x: nd.erf(x),
     lambda x: __import__("scipy.special", fromlist=["erf"]).erf(x), (-2, 2)),
    ("log1p", lambda x: nd.log1p(x), onp.log1p, (-0.5, 3)),
    ("expm1", lambda x: nd.expm1(x), onp.expm1, (-2, 2)),
    ("floor", lambda x: nd.floor(x), onp.floor, (-3, 3)),
    ("ceil", lambda x: nd.ceil(x), onp.ceil, (-3, 3)),
    ("sign", lambda x: nd.sign(x), onp.sign, (-2, 2)),
    ("reciprocal", lambda x: nd.reciprocal(x), lambda x: 1 / x, (0.2, 3)),
    ("gamma", lambda x: nd.gamma(x),
     lambda x: __import__("scipy.special", fromlist=["gamma"]).gamma(x),
     (0.5, 4)),
    ("gammaln", lambda x: nd.gammaln(x),
     lambda x: __import__("scipy.special", fromlist=["gammaln"]).gammaln(x),
     (0.5, 4)),
]


@pytest.mark.parametrize("case", UNARY_CASES, ids=lambda c: c[0])
def test_unary_forward(case):
    name, fn, ref, (lo, hi) = case
    x = _a((3, 7), lo, hi)
    out = fn(nd.array(x)).asnumpy()
    onp.testing.assert_allclose(out, ref(x), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("case", [c for c in UNARY_CASES if c[0] in
                                  ("sigmoid", "tanh", "exp", "log", "sqrt",
                                   "square", "sin", "cos", "log1p")],
                         ids=lambda c: c[0])
def test_unary_numeric_gradient(case):
    name, fn, ref, (lo, hi) = case
    x = _a((4, 5), lo + 0.2, hi)
    check_numeric_gradient(lambda a: fn(a).sum(), [nd.array(x)])


BINARY_CASES = [
    ("add", lambda a, b: a + b, onp.add),
    ("sub", lambda a, b: a - b, onp.subtract),
    ("mul", lambda a, b: a * b, onp.multiply),
    ("div", lambda a, b: a / b, onp.divide),
    ("pow", lambda a, b: nd.power(nd.abs(a) + 0.5, b),
     lambda a, b: onp.power(onp.abs(a) + 0.5, b)),
    ("maximum", nd.maximum, onp.maximum),
    ("minimum", nd.minimum, onp.minimum),
    ("hypot", nd.hypot, onp.hypot),
]


@pytest.mark.parametrize("case", BINARY_CASES, ids=lambda c: c[0])
def test_binary_forward_broadcast(case):
    name, fn, ref = case
    a, b = _a((4, 1, 5)), _a((1, 3, 5), 0.5, 2.0)
    out = fn(nd.array(a), nd.array(b)).asnumpy()
    onp.testing.assert_allclose(out, ref(a, b), rtol=2e-5, atol=2e-5)


REDUCE_CASES = [
    ("sum", lambda x, ax: nd.sum(x, axis=ax), onp.sum),
    ("mean", lambda x, ax: nd.mean(x, axis=ax), onp.mean),
    ("max", lambda x, ax: nd.max(x, axis=ax), onp.max),
    ("min", lambda x, ax: nd.min(x, axis=ax), onp.min),
    ("prod", lambda x, ax: nd.prod(x, axis=ax), onp.prod),
]


@pytest.mark.parametrize("case", REDUCE_CASES, ids=lambda c: c[0])
@pytest.mark.parametrize("axis", [0, 1, (0, 2), None])
def test_reductions(case, axis):
    name, fn, ref = case
    x = _a((3, 4, 5), 0.5, 1.5)
    out = fn(nd.array(x), axis).asnumpy()
    onp.testing.assert_allclose(out, ref(x, axis=axis), rtol=1e-5, atol=1e-5)


def test_norm_ord():
    x = _a((4, 6))
    onp.testing.assert_allclose(nd.norm(nd.array(x)).asnumpy(),
                                onp.linalg.norm(x), rtol=1e-5)
    onp.testing.assert_allclose(
        nd.norm(nd.array(x), ord=1, axis=1).asnumpy(),
        onp.abs(x).sum(1), rtol=1e-5)


def test_dot_and_batch_dot_grads():
    a, b = _a((4, 6)), _a((6, 3))
    onp.testing.assert_allclose(nd.dot(nd.array(a), nd.array(b)).asnumpy(),
                                a @ b, rtol=1e-5, atol=1e-5)
    check_numeric_gradient(
        lambda x, y: nd.dot(x, y).sum(), [nd.array(a), nd.array(b)])
    ba, bb = _a((2, 4, 5)), _a((2, 5, 3))
    onp.testing.assert_allclose(
        nd.batch_dot(nd.array(ba), nd.array(bb)).asnumpy(),
        onp.einsum("bij,bjk->bik", ba, bb), rtol=1e-5, atol=1e-5)


def test_indexing_family():
    x = _a((5, 7))
    xa = nd.array(x)
    idx = nd.array(onp.array([0, 2, 4], "int32"))
    onp.testing.assert_allclose(nd.take(xa, idx).asnumpy(), x[[0, 2, 4]])
    oh = nd.one_hot(idx, 5).asnumpy()
    assert oh.shape == (3, 5) and oh.sum() == 3
    # MXNet gather_nd: indices are (index_dims, N) — output[n] =
    # data[ind[0,n], ind[1,n]] (reference tensor/indexing_op.h semantics)
    ind = nd.array(onp.array([[0, 1], [2, 3]], "int32"))
    g = nd.gather_nd(xa, ind)
    onp.testing.assert_allclose(g.asnumpy(), [x[0, 2], x[1, 3]])


def test_ordering_family():
    x = _a((3, 8))
    xa = nd.array(x)
    onp.testing.assert_allclose(nd.argmax(xa, axis=1).asnumpy(),
                                x.argmax(1))
    onp.testing.assert_allclose(nd.argmin(xa, axis=1).asnumpy(),
                                x.argmin(1))
    onp.testing.assert_allclose(nd.sort(xa, axis=1).asnumpy(),
                                onp.sort(x, 1), rtol=1e-6)
    onp.testing.assert_allclose(nd.argsort(xa, axis=1).asnumpy(),
                                onp.argsort(x, 1, kind="stable"))
    tk = nd.topk(xa, k=3, axis=1, ret_typ="value").asnumpy()
    onp.testing.assert_allclose(tk, -onp.sort(-x, 1)[:, :3], rtol=1e-6)


def test_matrix_manip_family():
    x = _a((2, 3, 4))
    xa = nd.array(x)
    onp.testing.assert_allclose(
        nd.transpose(xa, axes=(2, 0, 1)).asnumpy(), x.transpose(2, 0, 1))
    onp.testing.assert_allclose(
        nd.reshape(xa, (6, 4)).asnumpy(), x.reshape(6, 4))
    onp.testing.assert_allclose(nd.flip(xa, axis=1).asnumpy(),
                                x[:, ::-1])
    onp.testing.assert_allclose(nd.tile(xa, reps=(2, 1, 1)).asnumpy(),
                                onp.tile(x, (2, 1, 1)))
    onp.testing.assert_allclose(
        nd.repeat(xa, repeats=2, axis=0).asnumpy(), onp.repeat(x, 2, 0))
    onp.testing.assert_allclose(
        nd.expand_dims(xa, axis=1).asnumpy(), x[:, None])
    st = nd.stack(xa, xa, axis=0).asnumpy()
    onp.testing.assert_allclose(st, onp.stack([x, x]))
    cc = nd.concat(xa, xa, dim=2).asnumpy()
    onp.testing.assert_allclose(cc, onp.concatenate([x, x], 2))
    s = nd.slice(xa, begin=(0, 1, 0), end=(2, 3, 2)).asnumpy()
    onp.testing.assert_allclose(s, x[0:2, 1:3, 0:2])
    sa = nd.slice_axis(xa, axis=2, begin=1, end=3).asnumpy()
    onp.testing.assert_allclose(sa, x[:, :, 1:3])
    w = nd.where(nd.array((x > 0).astype("float32")), xa, -xa).asnumpy()
    onp.testing.assert_allclose(w, onp.where(x > 0, x, -x))
    cl = nd.clip(xa, -0.5, 0.5).asnumpy()
    onp.testing.assert_allclose(cl, onp.clip(x, -0.5, 0.5))


def test_softmax_family_and_grads():
    x = _a((4, 10))
    xa = nd.array(x)
    ref = onp.exp(x) / onp.exp(x).sum(1, keepdims=True)
    onp.testing.assert_allclose(nd.softmax(xa, axis=1).asnumpy(), ref,
                                rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(nd.log_softmax(xa, axis=1).asnumpy(),
                                onp.log(ref), rtol=1e-5, atol=1e-5)
    check_numeric_gradient(lambda a: (nd.softmax(a, axis=1) ** 2).sum(),
                           [nd.array(x)])


def test_higher_order_grad_still_works():
    # d2/dx2 of x^3 = 6x through create_graph
    x = nd.array(onp.array([1.0, 2.0], "float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = (x ** 3).sum()
        g1 = mx.autograd.grad(y, [x], create_graph=True)[0]
        g1s = g1.sum()
    g1s.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [6.0, 12.0], rtol=1e-5)


def test_linalg_family():
    a = _a((4, 4)) + 4 * onp.eye(4, dtype="float32")
    aa = nd.array(a)
    onp.testing.assert_allclose(nd.linalg_inverse(aa).asnumpy(),
                                onp.linalg.inv(a), rtol=1e-3, atol=1e-4)
    spd = a @ a.T + onp.eye(4, dtype="float32")
    onp.testing.assert_allclose(
        nd.linalg_potrf(nd.array(spd)).asnumpy(),
        onp.linalg.cholesky(spd), rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(
        nd.linalg_gemm2(aa, aa).asnumpy(), a @ a, rtol=1e-4, atol=1e-4)


def test_embedding_and_sequence():
    w = _a((10, 4))
    ids = onp.array([[1, 3], [5, 0]], "int32")
    out = nd.Embedding(nd.array(ids), nd.array(w), input_dim=10,
                       output_dim=4).asnumpy()
    onp.testing.assert_allclose(out, w[ids])
    x = _a((5, 2, 3))  # (T, B, C)
    lens = onp.array([3, 5], "float32")
    m = nd.SequenceMask(nd.array(x), nd.array(lens),
                        use_sequence_length=True).asnumpy()
    assert (m[3:, 0] == 0).all() and (m[:, 1] == x[:, 1]).all()
    last = nd.SequenceLast(nd.array(x), nd.array(lens),
                           use_sequence_length=True).asnumpy()
    onp.testing.assert_allclose(last[0], x[2, 0], rtol=1e-6)
    rev = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True).asnumpy()
    onp.testing.assert_allclose(rev[0, 0], x[2, 0], rtol=1e-6)


def test_linalg_extended():
    a = _a((4, 4)) + 4 * onp.eye(4, dtype="float32")
    spd = a @ a.T + onp.eye(4, dtype="float32")
    onp.testing.assert_allclose(nd.linalg_det(nd.array(a)).asnumpy(),
                                onp.linalg.det(a), rtol=1e-3)
    sign, logdet = nd.linalg_slogdet(nd.array(spd))
    s_ref, l_ref = onp.linalg.slogdet(spd)
    onp.testing.assert_allclose(sign.asnumpy(), s_ref, rtol=1e-5)
    onp.testing.assert_allclose(logdet.asnumpy(), l_ref, rtol=1e-4)
    # trsm: solve L X = B for lower-triangular L
    L = onp.linalg.cholesky(spd).astype("float32")
    B = _a((4, 3))
    X = nd.linalg_trsm(nd.array(L), nd.array(B)).asnumpy()
    onp.testing.assert_allclose(L @ X, B, rtol=1e-4, atol=1e-4)
    # trmm
    Y = nd.linalg_trmm(nd.array(L), nd.array(B)).asnumpy()
    onp.testing.assert_allclose(Y, L @ B, rtol=1e-4, atol=1e-4)
    # syevd
    U, lam = nd.linalg_syevd(nd.array(spd))
    U, lam = U.asnumpy(), lam.asnumpy()
    onp.testing.assert_allclose(U.T @ onp.diag(lam) @ U, spd,
                                rtol=1e-3, atol=1e-3)
    # sumlogdiag
    onp.testing.assert_allclose(
        nd.linalg_sumlogdiag(nd.array(spd)).asnumpy(),
        onp.log(onp.diag(spd)).sum(), rtol=1e-5)
