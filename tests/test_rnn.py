"""gluon.rnn tests: golden numerics vs torch, cell/fused equivalence, grads.

Mirrors the reference's RNN test strategy (tests/python/unittest/
test_gluon_rnn.py: consistency of fused layer vs unrolled cells, shape
checks, hybridize parity).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def _set(p, arr):
    p.set_data(mx.nd.array(arr))


def test_lstm_matches_torch():
    torch = pytest.importorskip("torch")
    T, N, C, H = 5, 3, 4, 6
    x = onp.random.rand(T, N, C).astype("float32")
    ref = torch.nn.LSTM(C, H, num_layers=1)
    net = gluon.rnn.LSTM(H, input_size=C)
    net.initialize()
    _set(net.l0_i2h_weight, ref.weight_ih_l0.detach().numpy())
    _set(net.l0_h2h_weight, ref.weight_hh_l0.detach().numpy())
    _set(net.l0_i2h_bias, ref.bias_ih_l0.detach().numpy())
    _set(net.l0_h2h_bias, ref.bias_hh_l0.detach().numpy())
    want, _ = ref(torch.from_numpy(x))
    got = net(mx.nd.array(x))
    onp.testing.assert_allclose(got.asnumpy(), want.detach().numpy(),
                                rtol=1e-5, atol=1e-5)


def test_gru_matches_torch():
    torch = pytest.importorskip("torch")
    T, N, C, H = 5, 3, 4, 6
    x = onp.random.rand(T, N, C).astype("float32")
    ref = torch.nn.GRU(C, H, num_layers=1)
    net = gluon.rnn.GRU(H, input_size=C)
    net.initialize()
    _set(net.l0_i2h_weight, ref.weight_ih_l0.detach().numpy())
    _set(net.l0_h2h_weight, ref.weight_hh_l0.detach().numpy())
    _set(net.l0_i2h_bias, ref.bias_ih_l0.detach().numpy())
    _set(net.l0_h2h_bias, ref.bias_hh_l0.detach().numpy())
    want, _ = ref(torch.from_numpy(x))
    got = net(mx.nd.array(x))
    onp.testing.assert_allclose(got.asnumpy(), want.detach().numpy(),
                                rtol=1e-5, atol=1e-5)


def test_bidirectional_multilayer_matches_torch():
    torch = pytest.importorskip("torch")
    T, N, C, H = 4, 2, 3, 5
    x = onp.random.rand(T, N, C).astype("float32")
    ref = torch.nn.LSTM(C, H, num_layers=2, bidirectional=True)
    net = gluon.rnn.LSTM(H, num_layers=2, bidirectional=True, input_size=C)
    net.initialize()
    for layer in range(2):
        for pre, sfx in (("l", ""), ("r", "_reverse")):
            _set(getattr(net, f"{pre}{layer}_i2h_weight"),
                 getattr(ref, f"weight_ih_l{layer}{sfx}").detach().numpy())
            _set(getattr(net, f"{pre}{layer}_h2h_weight"),
                 getattr(ref, f"weight_hh_l{layer}{sfx}").detach().numpy())
            _set(getattr(net, f"{pre}{layer}_i2h_bias"),
                 getattr(ref, f"bias_ih_l{layer}{sfx}").detach().numpy())
            _set(getattr(net, f"{pre}{layer}_h2h_bias"),
                 getattr(ref, f"bias_hh_l{layer}{sfx}").detach().numpy())
    want, (hn, cn) = ref(torch.from_numpy(x))
    got, (h, c) = net(mx.nd.array(x), net.begin_state(N))
    onp.testing.assert_allclose(got.asnumpy(), want.detach().numpy(),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(h.asnumpy(), hn.detach().numpy(),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(c.asnumpy(), cn.detach().numpy(),
                                rtol=1e-5, atol=1e-5)


def test_cell_unroll_matches_fused_layer():
    T, N, C, H = 6, 2, 3, 4
    x = mx.nd.random.uniform(shape=(T, N, C))
    layer = gluon.rnn.LSTM(H, input_size=C)
    layer.initialize()
    cell = gluon.rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    outs, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    want = layer(x)
    onp.testing.assert_allclose(outs.asnumpy(), want.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_rnn_layer_gradients_flow():
    net = gluon.rnn.GRU(8, num_layers=2, bidirectional=True)
    net.initialize()
    x = mx.nd.random.uniform(shape=(5, 3, 4))
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    for name, p in net.collect_params().items():
        assert p.data().fresh_grad, name
        assert float(abs(p.grad().asnumpy()).max()) > 0, name


def test_rnn_layer_hybridize_consistency():
    net = gluon.rnn.LSTM(8, num_layers=2)
    net.initialize()
    x = mx.nd.random.uniform(shape=(5, 3, 4))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    onp.testing.assert_allclose(y_eager, y_hyb, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_rnn_training_converges():
    """Tiny sequence-sum regression learns (LSTM LM baseline smoke,
    BASELINE config 4)."""
    mx.random.seed(42)
    onp.random.seed(42)
    net = gluon.nn.HybridSequential()
    net.add(gluon.rnn.LSTM(16))
    net.add(gluon.nn.Dense(1))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    X = onp.random.rand(8, 10, 2).astype("float32")  # N,T,C -> TNC below
    Y = X.sum(axis=(1, 2), keepdims=False).reshape(8, 1)
    x = mx.nd.array(X.transpose(1, 0, 2))
    y = mx.nd.array(Y)
    l2 = gluon.loss.L2Loss()
    losses = []
    for _ in range(30):
        with autograd.record():
            seq = net[0](x)          # (T, N, 16)
            pred = net[1](seq[-1])   # last step
            loss = l2(pred, y)
        loss.backward()
        trainer.step(8)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.5, losses


def test_zoneout_and_dropout_cells():
    cell = gluon.rnn.SequentialRNNCell()
    cell.add(gluon.rnn.DropoutCell(0.3))
    cell.add(gluon.rnn.ZoneoutCell(gluon.rnn.RNNCell(6), 0.2, 0.2))
    cell.initialize()
    outs, st = cell.unroll(4, mx.nd.random.uniform(shape=(2, 4, 3)),
                           layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 4, 6)


# ---------------------------------------------------------------------------
# fused_rnn multi-layer bidirectional dropout: structure + kernel parity
# (PR 10 satellite: only single-direction parity was pinned before)
# ---------------------------------------------------------------------------

def _ml_bidir_args(mode, T=4, N=3, C=5, H=6, L=2, seed=7):
    from mxnet_tpu.ops.rnn import GATES
    g = GATES[mode]
    r = onp.random.RandomState(seed)
    params = []
    for layer in range(L):
        in_sz = C if layer == 0 else 2 * H
        for _ in range(2):   # directions
            params += [
                (r.randn(g * H, in_sz) * 0.3).astype("f4"),
                (r.randn(g * H, H) * 0.3).astype("f4"),
                (r.randn(g * H) * 0.1).astype("f4"),
                (r.randn(g * H) * 0.1).astype("f4"),
            ]
    x = (r.randn(T, N, C) * 0.5).astype("f4")
    h0 = (r.randn(L * 2, N, H) * 0.5).astype("f4")
    c0 = (r.randn(L * 2, N, H) * 0.5).astype("f4") \
        if mode == "lstm" else None
    return x, h0, c0, params


@pytest.mark.parametrize("mode", ["lstm", "gru"])
def test_fused_rnn_multilayer_bidir_dropout_structure(mode):
    """Pin the reference RNN op's inter-layer dropout placement for
    the BIDIRECTIONAL stack: dropout applies ONCE to the concatenated
    fwd+bwd layer output (not per direction), between layers only,
    with the gate ordering of each direction unchanged — verified
    against a manual per-direction composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.rnn import fused_rnn, scan_reference

    x, h0, c0, params = _ml_bidir_args(mode)
    jparams = [jnp.asarray(p) for p in params]
    key = jax.random.PRNGKey(3)
    p_drop = 0.5
    y, h_out, c_out = fused_rnn(
        jnp.asarray(x), jnp.asarray(h0),
        jnp.asarray(c0) if c0 is not None else None,
        jparams, mode, 2, True, dropout=p_drop, train=True, key=key)

    # manual composition mirroring the documented semantics
    inp = jnp.asarray(x)
    k = key
    hs, cs = [], []
    for layer in range(2):
        outs = []
        for d in range(2):
            idx = (layer * 2 + d) * 4
            w_ih, w_hh, b_ih, b_hh = jparams[idx:idx + 4]
            s = layer * 2 + d
            c0_s = jnp.asarray(c0)[s] if c0 is not None else None
            xw = inp @ w_ih.T + b_ih
            ys, h_t, c_t = scan_reference(
                xw, jnp.asarray(h0)[s], c0_s, w_hh, b_hh, mode,
                reverse=(d == 1))
            outs.append(ys)
            hs.append(h_t)
            if c_t is not None:
                cs.append(c_t)
        inp = jnp.concatenate(outs, axis=-1)
        if layer < 1:   # between layers only, ONE mask for the concat
            k, sub = jax.random.split(k)
            keep = jax.random.bernoulli(sub, 1.0 - p_drop, inp.shape)
            inp = jnp.where(keep, inp / (1.0 - p_drop), 0.0)
    assert bool((y == inp).all())
    assert bool((h_out == jnp.stack(hs, axis=0)).all())
    if c0 is not None:
        assert bool((c_out == jnp.stack(cs, axis=0)).all())


@pytest.mark.parametrize("mode", ["lstm", "gru"])
def test_fused_rnn_multilayer_bidir_dropout_kernel_parity(
        monkeypatch, mode):
    """Kernel tier vs XLA reference for the multi-layer bidirectional
    stack WITH inter-layer dropout: dropout lives outside the scan, so
    the same key gives identical masks and (at lane-aligned dims)
    bit-identical outputs on both paths."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.rnn import fused_rnn

    x, h0, c0, params = _ml_bidir_args(mode, T=5, N=8, C=16, H=128)
    jparams = [jnp.asarray(p) for p in params]
    key = jax.random.PRNGKey(11)

    def run():
        return fused_rnn(
            jnp.asarray(x), jnp.asarray(h0),
            jnp.asarray(c0) if c0 is not None else None,
            jparams, mode, 2, True, dropout=0.4, train=True, key=key)

    monkeypatch.setenv("MXNET_PALLAS", "off")
    y_r, h_r, c_r = run()
    monkeypatch.setenv("MXNET_PALLAS", "on")
    y_k, h_k, c_k = run()
    assert bool((y_r == y_k).all())
    assert bool((h_r == h_k).all())
    if c_r is not None:
        assert bool((c_r == c_k).all())


@pytest.mark.parametrize("cell_cls,kwargs", [
    (gluon.rnn.LSTMCell, {}),
    (gluon.rnn.GRUCell, {}),
    (gluon.rnn.RNNCell, {"activation": "tanh"}),
])
def test_cell_unroll_fused_dispatch_parity(cell_cls, kwargs):
    """PR 10 unroller dispatch: a plain gated cell's unroll over a
    merged tensor routes through the fused recurrence — same outputs
    (and output STRUCTURE) as the reference per-step loop, merged and
    unmerged, with states matching."""
    mx.random.seed(3)
    cell = cell_cls(6, input_size=4, **kwargs)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 5, 4))   # NTC

    # reference loop (the base-class implementation, forced)
    steps = [x.take(i, axis=1) for i in range(5)]
    st0 = cell.begin_state(2)
    states = list(st0)
    ref_outs = []
    for i in range(5):
        out, states = cell(steps[i], states)
        ref_outs.append(out.asnumpy())
    ref_states = [s.asnumpy() for s in states]

    merged, mstates = cell.unroll(5, x, begin_state=list(st0),
                                  layout="NTC", merge_outputs=True)
    assert merged.shape == (2, 5, 6)
    for i in range(5):
        onp.testing.assert_allclose(
            merged.asnumpy()[:, i], ref_outs[i], rtol=1e-5, atol=1e-6)
    for a, b in zip(mstates, ref_states):
        onp.testing.assert_allclose(a.asnumpy(), b, rtol=1e-5,
                                    atol=1e-6)

    listed, lstates = cell.unroll(5, x, begin_state=list(st0),
                                  layout="NTC", merge_outputs=False)
    assert isinstance(listed, list) and len(listed) == 5
    assert listed[0].shape == (2, 6)
    onp.testing.assert_allclose(listed[3].asnumpy(), ref_outs[3],
                                rtol=1e-5, atol=1e-6)
    # a step LIST keeps the reference loop (identical results)
    listed2, _ = cell.unroll(5, steps, begin_state=list(st0),
                             layout="NTC", merge_outputs=False)
    for a, b in zip(listed2, ref_outs):
        onp.testing.assert_allclose(a.asnumpy(), b, rtol=1e-6,
                                    atol=1e-7)
