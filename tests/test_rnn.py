"""gluon.rnn tests: golden numerics vs torch, cell/fused equivalence, grads.

Mirrors the reference's RNN test strategy (tests/python/unittest/
test_gluon_rnn.py: consistency of fused layer vs unrolled cells, shape
checks, hybridize parity).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def _set(p, arr):
    p.set_data(mx.nd.array(arr))


def test_lstm_matches_torch():
    torch = pytest.importorskip("torch")
    T, N, C, H = 5, 3, 4, 6
    x = onp.random.rand(T, N, C).astype("float32")
    ref = torch.nn.LSTM(C, H, num_layers=1)
    net = gluon.rnn.LSTM(H, input_size=C)
    net.initialize()
    _set(net.l0_i2h_weight, ref.weight_ih_l0.detach().numpy())
    _set(net.l0_h2h_weight, ref.weight_hh_l0.detach().numpy())
    _set(net.l0_i2h_bias, ref.bias_ih_l0.detach().numpy())
    _set(net.l0_h2h_bias, ref.bias_hh_l0.detach().numpy())
    want, _ = ref(torch.from_numpy(x))
    got = net(mx.nd.array(x))
    onp.testing.assert_allclose(got.asnumpy(), want.detach().numpy(),
                                rtol=1e-5, atol=1e-5)


def test_gru_matches_torch():
    torch = pytest.importorskip("torch")
    T, N, C, H = 5, 3, 4, 6
    x = onp.random.rand(T, N, C).astype("float32")
    ref = torch.nn.GRU(C, H, num_layers=1)
    net = gluon.rnn.GRU(H, input_size=C)
    net.initialize()
    _set(net.l0_i2h_weight, ref.weight_ih_l0.detach().numpy())
    _set(net.l0_h2h_weight, ref.weight_hh_l0.detach().numpy())
    _set(net.l0_i2h_bias, ref.bias_ih_l0.detach().numpy())
    _set(net.l0_h2h_bias, ref.bias_hh_l0.detach().numpy())
    want, _ = ref(torch.from_numpy(x))
    got = net(mx.nd.array(x))
    onp.testing.assert_allclose(got.asnumpy(), want.detach().numpy(),
                                rtol=1e-5, atol=1e-5)


def test_bidirectional_multilayer_matches_torch():
    torch = pytest.importorskip("torch")
    T, N, C, H = 4, 2, 3, 5
    x = onp.random.rand(T, N, C).astype("float32")
    ref = torch.nn.LSTM(C, H, num_layers=2, bidirectional=True)
    net = gluon.rnn.LSTM(H, num_layers=2, bidirectional=True, input_size=C)
    net.initialize()
    for layer in range(2):
        for pre, sfx in (("l", ""), ("r", "_reverse")):
            _set(getattr(net, f"{pre}{layer}_i2h_weight"),
                 getattr(ref, f"weight_ih_l{layer}{sfx}").detach().numpy())
            _set(getattr(net, f"{pre}{layer}_h2h_weight"),
                 getattr(ref, f"weight_hh_l{layer}{sfx}").detach().numpy())
            _set(getattr(net, f"{pre}{layer}_i2h_bias"),
                 getattr(ref, f"bias_ih_l{layer}{sfx}").detach().numpy())
            _set(getattr(net, f"{pre}{layer}_h2h_bias"),
                 getattr(ref, f"bias_hh_l{layer}{sfx}").detach().numpy())
    want, (hn, cn) = ref(torch.from_numpy(x))
    got, (h, c) = net(mx.nd.array(x), net.begin_state(N))
    onp.testing.assert_allclose(got.asnumpy(), want.detach().numpy(),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(h.asnumpy(), hn.detach().numpy(),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(c.asnumpy(), cn.detach().numpy(),
                                rtol=1e-5, atol=1e-5)


def test_cell_unroll_matches_fused_layer():
    T, N, C, H = 6, 2, 3, 4
    x = mx.nd.random.uniform(shape=(T, N, C))
    layer = gluon.rnn.LSTM(H, input_size=C)
    layer.initialize()
    cell = gluon.rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    outs, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    want = layer(x)
    onp.testing.assert_allclose(outs.asnumpy(), want.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_rnn_layer_gradients_flow():
    net = gluon.rnn.GRU(8, num_layers=2, bidirectional=True)
    net.initialize()
    x = mx.nd.random.uniform(shape=(5, 3, 4))
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    for name, p in net.collect_params().items():
        assert p.data().fresh_grad, name
        assert float(abs(p.grad().asnumpy()).max()) > 0, name


def test_rnn_layer_hybridize_consistency():
    net = gluon.rnn.LSTM(8, num_layers=2)
    net.initialize()
    x = mx.nd.random.uniform(shape=(5, 3, 4))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    onp.testing.assert_allclose(y_eager, y_hyb, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_rnn_training_converges():
    """Tiny sequence-sum regression learns (LSTM LM baseline smoke,
    BASELINE config 4)."""
    mx.random.seed(42)
    onp.random.seed(42)
    net = gluon.nn.HybridSequential()
    net.add(gluon.rnn.LSTM(16))
    net.add(gluon.nn.Dense(1))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    X = onp.random.rand(8, 10, 2).astype("float32")  # N,T,C -> TNC below
    Y = X.sum(axis=(1, 2), keepdims=False).reshape(8, 1)
    x = mx.nd.array(X.transpose(1, 0, 2))
    y = mx.nd.array(Y)
    l2 = gluon.loss.L2Loss()
    losses = []
    for _ in range(30):
        with autograd.record():
            seq = net[0](x)          # (T, N, 16)
            pred = net[1](seq[-1])   # last step
            loss = l2(pred, y)
        loss.backward()
        trainer.step(8)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.5, losses


def test_zoneout_and_dropout_cells():
    cell = gluon.rnn.SequentialRNNCell()
    cell.add(gluon.rnn.DropoutCell(0.3))
    cell.add(gluon.rnn.ZoneoutCell(gluon.rnn.RNNCell(6), 0.2, 0.2))
    cell.initialize()
    outs, st = cell.unroll(4, mx.nd.random.uniform(shape=(2, 4, 3)),
                           layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 4, 6)
