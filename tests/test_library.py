"""Out-of-tree op library loading (reference MXLoadLib + lib_api.h C ABI;
example/extensions/lib_custom_op). Builds a real shared library with g++ at
test time, loads it with mx.library.load, and runs its ops eagerly and
inside a jit."""
import os
import subprocess

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError

LIB_SRC = r"""
#include <cstring>
extern "C" {

static const char* kNames[2] = {"lib_gelu_host", "lib_weighted_sum"};

int mxt_lib_num_ops(void) { return 2; }

const char* mxt_lib_op_name(int op) { return kNames[op]; }

static long numel(const long* shape, int ndim) {
  long n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

int mxt_lib_op_infer_shape(int op, const long* const* in_shapes,
                           const int* in_ndims, int n_in,
                           long* out_shape, int* out_ndim) {
  (void)op; (void)n_in;
  *out_ndim = in_ndims[0];
  std::memcpy(out_shape, in_shapes[0], in_ndims[0] * sizeof(long));
  return 0;
}

int mxt_lib_op_forward(int op, const float* const* ins,
                       const long* const* in_shapes, const int* in_ndims,
                       int n_in, float* out, const long* out_shape,
                       int out_ndim) {
  long n = numel(out_shape, out_ndim);
  if (op == 0) {  // tanh-free "gelu": x * sigmoid(1.702 x)
    for (long i = 0; i < n; ++i) {
      float x = ins[0][i];
      float s = 1.0f / (1.0f + __builtin_expf(-1.702f * x));
      out[i] = x * s;
    }
    return 0;
  }
  if (op == 1) {  // 0.25*a + 0.75*b
    if (n_in != 2 || numel(in_shapes[1], in_ndims[1]) != n) return 2;
    for (long i = 0; i < n; ++i)
      out[i] = 0.25f * ins[0][i] + 0.75f * ins[1][i];
    return 0;
  }
  return 1;
}

}  // extern "C"
"""


@pytest.fixture(scope="module")
def oplib(tmp_path_factory):
    d = tmp_path_factory.mktemp("oplib")
    src = d / "lib_ops.cc"
    so = d / "libops.so"
    src.write_text(LIB_SRC)
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(src),
                    "-o", str(so)], check=True)
    return str(so)


def test_load_and_run_eager(oplib):
    names = mx.library.load(oplib, verbose=False)
    assert names == ["lib_gelu_host", "lib_weighted_sum"]
    x = onp.linspace(-3, 3, 24, dtype="float32").reshape(4, 6)
    out = mx.nd.lib_gelu_host(mx.nd.array(x)).asnumpy()
    ref = x / (1.0 + onp.exp(-1.702 * x))
    onp.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    a = onp.ones((3, 3), "float32")
    b = onp.full((3, 3), 2.0, "float32")
    got = mx.nd.lib_weighted_sum(mx.nd.array(a), mx.nd.array(b)).asnumpy()
    onp.testing.assert_allclose(got, onp.full((3, 3), 1.75))


def test_library_op_inside_jit(oplib):
    """pure_callback makes the host op usable inside a compiled
    computation (the reference's async CustomOperator never blocking
    engine workers, custom-inl.h:103)."""
    mx.library.load(oplib, verbose=False)
    from mxnet_tpu.ops.registry import get_op
    op = get_op("lib_weighted_sum")

    @jax.jit
    def f(a, b):
        return op.fn(a, b) + 1.0

    got = onp.asarray(f(jnp.ones((2, 2)), jnp.full((2, 2), 2.0)))
    onp.testing.assert_allclose(got, onp.full((2, 2), 2.75))


def test_tensor_inspector():
    """Reference src/common/tensor_inspector.h: checkers, checksum, dump."""
    from mxnet_tpu.inspector import TensorInspector, CheckerType
    x = mx.nd.array(onp.array([[1.0, -2.0], [onp.nan, 4.0]], "float32"))
    ti = TensorInspector(x, tag="t")
    assert ti.check_value(CheckerType.NaNChecker) == [(1, 0)]
    assert ti.check_value(CheckerType.NegativeChecker) == [(0, 1)]
    assert ti.check_value(CheckerType.FiniteChecker) == [(1, 0)]
    clean = TensorInspector(mx.nd.ones((4, 4)))
    assert clean.check_value(CheckerType.AbnormalChecker) == []
    assert clean.checksum() == TensorInspector(mx.nd.ones((4, 4))).checksum()
    assert "shape=(2, 2)" in ti.to_string()


def test_nan_guard_names_offending_op(tmp_path):
    from mxnet_tpu import inspector, autograd
    inspector.install_nan_guard()
    try:
        with pytest.raises(MXNetError, match="log"):
            mx.nd.log(mx.nd.array([-1.0])).wait_to_read()
        # clean ops pass through
        mx.nd.sqrt(mx.nd.array([4.0])).wait_to_read()
        # under autograd.record the kernel runs inside jax.vjp tracing;
        # the guard must still fire on the concrete primal outputs
        a = mx.nd.array([0.5])
        a.attach_grad()
        with pytest.raises(MXNetError, match="log"):
            with autograd.record():
                mx.nd.log(a - 1.0)
    finally:
        inspector.remove_nan_guard()
    # dump_to_file round trip
    from mxnet_tpu.inspector import TensorInspector
    p = TensorInspector(mx.nd.ones((2,))).dump_to_file("w", str(tmp_path))
    onp.testing.assert_allclose(onp.load(p), onp.ones(2))


def test_load_rejects_non_library(oplib):
    with pytest.raises(MXNetError):
        mx.library.load("/usr/lib/x86_64-linux-gnu/libc.so.6",
                        verbose=False)
    # loading twice is idempotent
    n1 = mx.library.load(oplib, verbose=False)
    n2 = mx.library.load(oplib, verbose=False)
    assert n1 == n2
