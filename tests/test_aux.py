"""Aux subsystems: profiler, AMP, runtime features, custom ops, control flow.

Reference analogs: tests/python/unittest/{test_profiler.py, test_operator.py
control-flow sections, test_contrib_amp-style checks}.
"""
import json
import os

import numpy as onp
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


# ---------------------------------------------------------------------------
# profiler
# ---------------------------------------------------------------------------

def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "trace.json")
    mx.profiler.set_config(filename=fname, aggregate_stats=True)
    mx.profiler.set_state("run")
    a = mx.nd.ones((8, 8))
    b = (a * 2 + 1).sum()
    b.wait_to_read()
    mx.profiler.set_state("stop")
    table = mx.profiler.dumps()
    assert "Calls" in table and len(table.splitlines()) > 1
    mx.profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert events and all(e["ph"] == "X" for e in events)
    names = {e["name"] for e in events}
    assert any("mul" in n or "add" in n or "sum" in n for n in names), names


def test_per_op_device_attribution_name_stack():
    """Framework op names must flow into the XLA name stack (via
    jax.named_scope in the invoke funnel) so XProf device traces attribute
    kernels inside a jitted CachedOp back to framework ops — the analog of
    the reference's __profiler_scope__/ProfileOperator device annotation
    (src/profiler/profiler.h:251-299)."""
    import jax
    from mxnet_tpu.ndarray.ndarray import NDArray

    def f(x):
        a = NDArray(x)
        b = mx.nd.add(a, a)
        return mx.nd.sigmoid(b)._data

    jaxpr = jax.make_jaxpr(f)(jnp.ones((2, 2)))
    stacks = [str(e.source_info.name_stack) for e in jaxpr.eqns]
    assert any("add" in s for s in stacks), stacks
    assert any("sigmoid" in s for s in stacks), stacks
    # a Gluon block traced inside jit funnels per-op through invoke_raw the
    # same way, so a cached computation carries per-op scopes for every layer
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4, in_units=3, activation="relu")
    net.initialize()

    def g(xj):
        return net(NDArray(xj))._data

    stacks = [str(e.source_info.name_stack)
              for e in jax.make_jaxpr(g)(jnp.ones((2, 3))).eqns]
    assert any("fully_connected" in s for s in stacks), stacks
    assert any("activation" in s for s in stacks), stacks


def test_profiler_scope_and_pause(tmp_path):
    fname = str(tmp_path / "trace2.json")
    mx.profiler.set_config(filename=fname)
    mx.profiler.set_state("run")
    with mx.profiler.scope("blockA"):
        (mx.nd.ones((4,)) + 1).wait_to_read()
    mx.profiler.pause()
    (mx.nd.ones((4,)) * 3).wait_to_read()
    mx.profiler.resume()
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    assert any(e["name"].startswith("blockA:") for e in events)
    assert not any("mul" in e["name"] for e in events)  # paused op absent


# ---------------------------------------------------------------------------
# AMP
# ---------------------------------------------------------------------------

def test_amp_matmul_runs_bf16():
    from mxnet_tpu import amp
    amp.init("bfloat16")
    try:
        assert amp.is_enabled()
        a = mx.nd.ones((4, 8))
        b = mx.nd.ones((8, 4))
        out = mx.nd.dot(a, b)
        # f32 in, bf16 OUT: the low dtype flows between MXU ops (reference
        # FP16_FUNCS semantics) so activations stay half-width in HBM
        assert onp.dtype(out.dtype).name == "bfloat16", out.dtype
        onp.testing.assert_allclose(out.asnumpy().astype("float32"),
                                    8 * onp.ones((4, 4)))
        # f32-pinned op casts UP: bf16 in, f32 out
        s = mx.nd.softmax(out)
        assert s.dtype == onp.float32
        # f32 input to a pinned op stays f32
        s2 = mx.nd.softmax(mx.nd.ones((2, 3)))
        assert s2.dtype == onp.float32
    finally:
        amp.uninit()
    assert not amp.is_enabled()


def test_amp_training_converges():
    from mxnet_tpu import amp
    from mxnet_tpu.gluon import nn
    amp.init("bfloat16")
    try:
        net = nn.Dense(1, in_units=4)
        net.initialize()
        tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.1})
        amp.init_trainer(tr)
        rng = onp.random.RandomState(0)
        x = mx.nd.array(rng.randn(64, 4).astype("float32"))
        w_true = onp.array([[1.0, -2.0, 0.5, 3.0]], "float32")
        y = mx.nd.array(rng.randn(64, 4).astype("float32").dot(w_true.T) * 0)
        y = mx.nd.array(x.asnumpy().dot(w_true.T))
        losses = []
        for _ in range(30):
            with mx.autograd.record():
                out = net(x)
                loss = ((out - y) ** 2).mean()
            with amp.scale_loss(loss, tr) as scaled:
                scaled.backward()
            tr.step(1)
            losses.append(float(loss.asnumpy()))
        assert losses[-1] < losses[0] * 0.2, losses[::10]
    finally:
        amp.uninit()


def test_amp_convert_hybrid_block():
    from mxnet_tpu import amp
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.BatchNorm(in_channels=8),
            nn.Dense(2, in_units=8))
    net.initialize()
    x = mx.nd.ones((2, 4))
    net(x)
    amp.convert_hybrid_block(net, "bfloat16")
    dtypes = {p.name: p.dtype for p in net.collect_params().values()}
    dense_dtypes = [d for n, d in dtypes.items() if "batchnorm" not in n.lower()
                    and "gamma" not in n and "beta" not in n
                    and "running" not in n]
    assert all(str(d) == "bfloat16" for d in dense_dtypes), dtypes


def test_loss_scaler_dynamics():
    from mxnet_tpu.amp import LossScaler
    s = LossScaler(init_scale=1024., scale_window=2)
    s.update_scale(overflow=True)
    assert s.loss_scale == 512.
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 1024.
    assert s.has_overflow([mx.nd.array(onp.array([onp.inf]))])
    assert not s.has_overflow([mx.nd.array(onp.array([1.0]))])


# ---------------------------------------------------------------------------
# runtime features
# ---------------------------------------------------------------------------

def test_runtime_feature_list():
    feats = mx.runtime.Features()
    assert feats.is_enabled("XLA") and feats.is_enabled("PALLAS")
    assert not feats.is_enabled("CUDA")
    fl = mx.runtime.feature_list()
    assert any(f.name == "RECORDIO" and f.enabled for f in fl)


# ---------------------------------------------------------------------------
# custom ops (mx.operator)
# ---------------------------------------------------------------------------

def test_custom_op_forward_backward():
    import mxnet_tpu.operator as mxop

    @mxop.register("mysquare")
    class SquareProp(mxop.CustomOpProp):
        def create_operator(self, ctx, in_shapes, in_dtypes):
            class Square(mxop.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0] * in_data[0])

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0],
                                2 * in_data[0] * out_grad[0])
            return Square()

    x = mx.nd.array(onp.array([1., 2., 3.], "float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="mysquare")
        loss = y.sum()
    loss.backward()
    onp.testing.assert_allclose(y.asnumpy(), [1., 4., 9.])
    onp.testing.assert_allclose(x.grad.asnumpy(), [2., 4., 6.])


def test_custom_op_unregistered_errors():
    with pytest.raises(MXNetError, match="not registered"):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="nope")


# ---------------------------------------------------------------------------
# control flow ops
# ---------------------------------------------------------------------------

def test_foreach_cumsum_and_grad():
    from mxnet_tpu.ndarray import contrib
    data = mx.nd.array(onp.arange(6, dtype="float32").reshape(6, 1))
    init = mx.nd.zeros((1,))
    init.attach_grad()
    with mx.autograd.record():
        outs, final = contrib.foreach(
            lambda x, st: (x + st[0], [x + st[0]]), data, [init])
        loss = outs.sum()
    loss.backward()
    onp.testing.assert_allclose(
        outs.asnumpy().ravel(), onp.cumsum(onp.arange(6.)))
    assert float(init.grad.asnumpy()) == 6.0  # d(sum cumsum)/d(init)


def test_while_loop():
    from mxnet_tpu.ndarray import contrib
    # double until > 100
    outs, states = contrib.while_loop(
        cond=lambda i, x: (x < 100).sum(),
        func=lambda i, x: (i, [i + 1, x * 2]),
        loop_vars=[mx.nd.zeros((1,)), mx.nd.ones((1,))],
        max_iterations=20)
    assert float(states[1].asnumpy()) == 128.0
    assert float(states[0].asnumpy()) == 7.0


def test_cond():
    from mxnet_tpu.ndarray import contrib
    x = mx.nd.array(onp.array([3.0], "float32"))
    out = contrib.cond(x.sum() > 2, lambda: x * 10, lambda: x - 1)
    out = out[0] if isinstance(out, (list, tuple)) else out
    onp.testing.assert_allclose(out.asnumpy(), [30.0])


# ---------------------------------------------------------------------------
# detection ops
# ---------------------------------------------------------------------------

def test_box_iou():
    from mxnet_tpu.ndarray import contrib
    a = mx.nd.array(onp.array([[0, 0, 2, 2], [1, 1, 3, 3]], "float32"))
    b = mx.nd.array(onp.array([[0, 0, 2, 2]], "float32"))
    iou = contrib.box_iou(a, b).asnumpy()
    onp.testing.assert_allclose(iou[:, 0], [1.0, 1.0 / 7.0], rtol=1e-5)


def test_box_nms():
    from mxnet_tpu.ndarray import contrib
    # [id, score, x1, y1, x2, y2]
    boxes = onp.array([
        [0, 0.9, 0, 0, 10, 10],
        [0, 0.8, 1, 1, 11, 11],    # big overlap with first -> suppressed
        [0, 0.7, 20, 20, 30, 30],  # far away -> kept
        [1, 0.6, 0, 0, 10, 10],    # other class -> kept
        [0, 0.0, 0, 0, 1, 1],      # below valid_thresh -> dropped
    ], "float32")
    out = contrib.box_nms(mx.nd.array(boxes), overlap_thresh=0.5,
                          valid_thresh=0.1, id_index=0).asnumpy()
    kept = out[out[:, 1] > 0]
    onp.testing.assert_allclose(sorted(kept[:, 1].tolist()),
                                [0.6, 0.7, 0.9], rtol=1e-6)


def test_roi_align():
    from mxnet_tpu.ndarray import contrib
    # constant image -> pooled output equals the constant
    data = mx.nd.ones((1, 2, 16, 16)) * 5.0
    rois = mx.nd.array(onp.array([[0, 2, 2, 10, 10]], "float32"))
    out = contrib.ROIAlign(data, rois, pooled_size=(4, 4), spatial_scale=1.0)
    assert out.shape == (1, 2, 4, 4)
    onp.testing.assert_allclose(out.asnumpy(), 5.0 * onp.ones((1, 2, 4, 4)),
                                rtol=1e-5)
    # gradient flows to data
    d = mx.nd.ones((1, 1, 8, 8))
    d.attach_grad()
    with mx.autograd.record():
        o = contrib.ROIAlign(d, mx.nd.array(onp.array([[0, 0, 0, 7, 7]],
                                                      "float32")),
                             pooled_size=2, spatial_scale=1.0)
        s = o.sum()
    s.backward()
    assert float(d.grad.asnumpy().sum()) > 0


def test_roi_align_padded_and_ps():
    from mxnet_tpu.ndarray import contrib
    data = mx.nd.ones((2, 8, 6, 6))
    # padded ROI (batch_idx -1) must be all zeros
    rois = mx.nd.array(onp.array([[0, 0, 0, 5, 5], [-1, 0, 0, 5, 5]],
                                 "float32"))
    out = contrib.ROIAlign(data, rois, pooled_size=2, spatial_scale=1.0)
    onp.testing.assert_allclose(out.asnumpy()[0], onp.ones((8, 2, 2)),
                                rtol=1e-5)
    onp.testing.assert_allclose(out.asnumpy()[1], onp.zeros((8, 2, 2)))
    # position-sensitive: C=8, PH*PW=4 -> out channel dim 2
    ps = contrib.ROIAlign(data, rois, pooled_size=2, spatial_scale=1.0,
                          position_sensitive=True)
    assert ps.shape == (2, 2, 2, 2)
    # adaptive sampling path (sample_ratio<=0) runs
    ad = contrib.ROIAlign(data, rois, pooled_size=2, spatial_scale=1.0,
                          sample_ratio=-1)
    onp.testing.assert_allclose(ad.asnumpy()[0], onp.ones((8, 2, 2)),
                                rtol=1e-5)


def test_box_nms_out_format():
    from mxnet_tpu.ndarray import contrib
    center = onp.array([[0, 0.9, 5, 5, 10, 10]], "float32")  # cx,cy,w,h
    out = contrib.box_nms(mx.nd.array(center), in_format="center",
                          out_format="corner").asnumpy()
    onp.testing.assert_allclose(out[0, 2:], [0, 0, 10, 10], rtol=1e-5)


def test_multibox_prior():
    from mxnet_tpu.ndarray import contrib
    data = mx.nd.ones((1, 8, 4, 4))
    anchors = contrib.MultiBoxPrior(data, sizes=(0.5, 0.25),
                                    ratios=(1.0, 2.0))
    # num_anchors = 2 + 2 - 1 = 3 per position
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()[0]
    # first anchor at (0,0): center (0.125, 0.125), size 0.5
    onp.testing.assert_allclose(a[0], [0.125 - 0.25, 0.125 - 0.25,
                                       0.125 + 0.25, 0.125 + 0.25],
                                rtol=1e-5)
    # width/height of ratio-2 anchor: w = 0.5*sqrt(2), h = 0.5/sqrt(2)
    w = a[2, 2] - a[2, 0]
    h = a[2, 3] - a[2, 1]
    onp.testing.assert_allclose(w / h, 2.0, rtol=1e-5)


def test_multibox_target_and_detection_roundtrip():
    from mxnet_tpu.ndarray import contrib
    # 4 hand-built anchors; one gt box aligned with anchor 1
    anchors = onp.array([[0.0, 0.0, 0.3, 0.3],
                         [0.3, 0.3, 0.7, 0.7],
                         [0.6, 0.6, 1.0, 1.0],
                         [0.0, 0.6, 0.4, 1.0]], "float32")[None]
    gt = onp.array([[[1.0, 0.32, 0.28, 0.72, 0.68]]], "float32")  # cls 1
    cls_pred = onp.zeros((1, 3, 4), "float32")
    bt, mask, ct = contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(gt), mx.nd.array(cls_pred))
    ct = ct.asnumpy()[0]
    assert ct[1] == 2.0          # gt cls 1 -> target 2 (0 is background)
    assert ct[0] == 0.0 and ct[2] == 0.0
    mask = mask.asnumpy().reshape(4, 4)
    assert mask[1].sum() == 4 and mask[0].sum() == 0

    # decode: feed perfect loc targets back -> recovered gt box
    bt = bt.asnumpy().reshape(1, -1)
    cls_prob = onp.zeros((1, 3, 4), "float32")
    cls_prob[0, 1, 1] = 0.9      # class 0 (fg) on anchor 1
    out = contrib.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(bt), mx.nd.array(anchors),
        threshold=0.5).asnumpy()[0]
    kept = out[out[:, 1] > 0]
    assert len(kept) == 1
    onp.testing.assert_allclose(kept[0, 2:], gt[0, 0, 1:], atol=1e-5)


def test_multibox_target_padded_labels_keep_forced_match():
    from mxnet_tpu.ndarray import contrib
    # low-IoU gt (only force-match applies) + a padding row whose argmax
    # would collide with the real gt's best anchor
    anchors = onp.array([[0.0, 0.0, 0.4, 0.4],
                         [0.5, 0.5, 0.9, 0.9]], "float32")[None]
    labels = onp.array([[[1.0, 0.0, 0.0, 0.2, 0.2],
                         [-1.0, 0.0, 0.0, 0.0, 0.0]]], "float32")
    cls_pred = onp.zeros((1, 3, 2), "float32")
    bt, mask, ct = contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(labels), mx.nd.array(cls_pred))
    ct = ct.asnumpy()[0]
    assert ct[0] == 2.0, ct      # forced match survives the padding row
    assert mask.asnumpy().reshape(2, 4)[0].sum() == 4


def test_multibox_target_negative_mining_thresh():
    from mxnet_tpu.ndarray import contrib
    anchors = onp.array([[0.0, 0.0, 0.4, 0.4],     # matched (forced)
                         [0.02, 0.02, 0.42, 0.42],  # near-miss IoU>0.4
                         [0.6, 0.6, 0.9, 0.9]], "float32")[None]
    labels = onp.array([[[0.0, 0.0, 0.0, 0.4, 0.4]]], "float32")
    cls_pred = onp.zeros((1, 2, 3), "float32")
    _, _, ct = contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(labels), mx.nd.array(cls_pred),
        overlap_threshold=0.9, negative_mining_ratio=1.0,
        negative_mining_thresh=0.4)
    ct = ct.asnumpy()[0]
    assert ct[0] == 1.0          # positive
    assert ct[1] == -1.0         # near-miss: excluded from negatives
    assert ct[2] == 0.0          # true negative kept


def test_profiler_memory_dump_and_summary(tmp_path):
    """Storage-profiler parity (reference src/profiler/storage_profiler.cc):
    pprof-format device memory snapshot + live-byte summary."""
    live = mx.nd.ones((512, 512))  # keep a buffer alive for the snapshot
    live.wait_to_read()
    try:
        p = mx.profiler.dump_memory(str(tmp_path / "mem.pprof"))
    except MXNetError as e:
        assert "axon" in str(e)  # tunneled plugin: refusal is the contract
        pytest.skip("device memory profile unsupported on this PjRt plugin")
    assert os.path.getsize(p) > 0
    summary = mx.profiler.memory_summary()
    # routed through the telemetry catalog (mx_mem_device_* gauges):
    # every device reports, with its accounting source named —
    # allocator counters where the PjRt client has them, the documented
    # live-array fallback (XLA:CPU) otherwise — never silent Nones
    assert summary
    for dev, stats in summary.items():
        assert set(stats) == {"bytes_in_use", "peak_bytes_in_use",
                              "bytes_limit", "source"}
        assert stats["source"] in ("allocator", "live_arrays")
        assert stats["bytes_in_use"] is not None
    # the live buffer above shows up somewhere (it sits on ONE of the
    # virtual mesh's devices; the others legitimately report 0)
    assert sum(s["bytes_in_use"] for s in summary.values()) > 0
    del live
