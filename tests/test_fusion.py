"""Fusion-census tests (mx.analysis.fusion — the arXiv:2301.13062
ideal-fusion audit): nested-fusion HLO parsing, the FLOP/boundary
models, golden known-bad programs (planted stranded transpose, planted
large f32 boundary materialization), the compute-/memory-bound
classification, the MXA005 unroll lint rule, and the per-leg baseline
regression gate over the checked-in tests/fixtures/fusion_baselines.json
(the tier-1 ``lint``-marked sweep at the bottom).
"""
import json
import os
import textwrap

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.analysis import fusion as afusion
from mxnet_tpu.analysis.hlo import parse_hlo
from mxnet_tpu.analysis.lint import lint_source
from mxnet_tpu.analysis.program import dtype_drift_scan, expect_mode, \
    host_transfer_scan
from mxnet_tpu.analysis.report import ProgramReport
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, nn, rnn
from mxnet_tpu.gluon import loss as gloss

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
BASELINES = os.path.join(FIXTURES, "fusion_baselines.json")


# ---------------------------------------------------------------------------
# nested-fusion HLO parsing
# ---------------------------------------------------------------------------

_NESTED_HLO = textwrap.dedent("""\
HloModule jit_step, is_scheduled=true, entry_computation_layout={(f32[64,64]{1,0})->f32[64,64]{1,0}}

%region_0.9 (Arg_0.10: f32[], Arg_1.11: f32[]) -> f32[] {
  %Arg_0.10 = f32[] parameter(0)
  %Arg_1.11 = f32[] parameter(1)
  ROOT %add.12 = f32[] add(f32[] %Arg_0.10, f32[] %Arg_1.11)
}

%fused_computation (param_0.1: f32[64,64]) -> f32[64,64] {
  %param_0.1 = f32[64,64]{1,0} parameter(0)
  %tanh.1 = f32[64,64]{1,0} tanh(f32[64,64]{1,0} %param_0.1)
  %convert.3 = f64[64,64]{1,0} convert(f32[64,64]{1,0} %tanh.1)
  %convert.4 = f32[64,64]{1,0} convert(f64[64,64]{1,0} %convert.3)
  ROOT %add.1 = f32[64,64]{1,0} add(f32[64,64]{1,0} %convert.4, f32[64,64]{1,0} %param_0.1)
}

ENTRY %main.1 (p0: f32[64,64]) -> f32[64,64] {
  %p0 = f32[64,64]{1,0} parameter(0)
  %dot.1 = f32[64,64]{1,0} dot(f32[64,64]{1,0} %p0, f32[64,64]{1,0} %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %tanh_add_fusion = f32[64,64]{1,0} fusion(f32[64,64]{1,0} %dot.1), kind=kLoop, calls=%fused_computation
  %constant.2 = f32[] constant(0)
  ROOT %reduce-window.1 = f32[64,64]{1,0} reduce-window(f32[64,64]{1,0} %tanh_add_fusion, f32[] %constant.2), window={size=1x1}, to_apply=%region_0.9
}
""")


def test_parser_builds_computations():
    mod = parse_hlo(_NESTED_HLO)
    assert set(mod.computations) == {"region_0.9", "fused_computation",
                                     "main.1"}
    assert mod.entry == "main.1"
    assert mod.computations["main.1"].is_entry
    # ops attached to their computation
    assert mod.ops["tanh.1"].computation == "fused_computation"
    assert mod.ops["dot.1"].computation == "main.1"
    assert mod.ops["add.12"].computation == "region_0.9"


def test_parser_links_fusion_bodies():
    mod = parse_hlo(_NESTED_HLO)
    fop = mod.ops["tanh_add_fusion"]
    assert fop.fusion_kind == "loop"
    assert fop.called == {"calls": ["fused_computation"]}
    body = [o.name for o in mod.fused_ops(fop)]
    assert body == ["param_0.1", "tanh.1", "convert.3", "convert.4",
                    "add.1"]
    # parent attribution from a body op back to its fusion
    assert mod.parent_fusion(mod.ops["tanh.1"]).name == "tanh_add_fusion"
    assert mod.parent_fusion(mod.ops["dot.1"]) is None


def test_parser_schedulable_vs_kernel_internal():
    mod = parse_hlo(_NESTED_HLO)
    sched = {c.name for c in mod.schedulable_computations()}
    assert sched == {"main.1"}
    assert mod.computations["fused_computation"].kernel_internal
    assert mod.computations["region_0.9"].kernel_internal  # to_apply
    # ROOT detection
    assert mod.ops["reduce-window.1"].is_root
    assert not mod.ops["dot.1"].is_root


def test_parser_typed_operands():
    mod = parse_hlo(_NESTED_HLO)
    dot = mod.ops["dot.1"]
    assert dot.operand_types == ["f32[64,64]{1,0}", "f32[64,64]{1,0}"]
    assert dot.operand_bytes(0) == 64 * 64 * 4
    # reduce-window's scalar init operand
    rw = mod.ops["reduce-window.1"]
    assert rw.operand_bytes(1) == 4


def test_parser_while_bodies_are_schedulable():
    hlo = textwrap.dedent("""\
    HloModule jit_loop, is_scheduled=true, entry_computation_layout={(s32[])->s32[]}

    %while_body (param.1: s32[]) -> s32[] {
      %param.1 = s32[] parameter(0)
      %constant.1 = s32[] constant(1)
      ROOT %add.1 = s32[] add(s32[] %param.1, s32[] %constant.1)
    }

    %while_cond (param.0: s32[]) -> pred[] {
      %param.0 = s32[] parameter(0)
      %constant.2 = s32[] constant(8)
      ROOT %compare.1 = pred[] compare(s32[] %param.0, s32[] %constant.2), direction=LT
    }

    ENTRY %main.1 (p0: s32[]) -> s32[] {
      %p0 = s32[] parameter(0)
      ROOT %while.1 = s32[] while(s32[] %p0), condition=%while_cond, body=%while_body
    }
    """)
    mod = parse_hlo(hlo)
    w = mod.ops["while.1"]
    assert w.called == {"condition": ["while_cond"],
                       "body": ["while_body"]}
    sched = {c.name for c in mod.schedulable_computations()}
    assert sched == {"main.1", "while_body", "while_cond"}


# ---------------------------------------------------------------------------
# FLOP model
# ---------------------------------------------------------------------------

def test_flop_model_dot_exact():
    mod = parse_hlo(_NESTED_HLO)
    # [64,64] @ [64,64]: 2*M*N*K
    assert afusion.op_flops(mod.ops["dot.1"]) == 2 * 64 * 64 * 64


def test_flop_model_convolution():
    line = ("  %convolution.1 = f32[1,8,8,4]{3,2,1,0} convolution("
            "f32[1,8,8,2]{3,2,1,0} %p0, f32[3,3,2,4]{3,2,1,0} %k), "
            "window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f")
    mod = parse_hlo("ENTRY %main.1 (p0: f32[1,8,8,2]) -> f32[1,8,8,4] "
                    "{\n" + line + "\n}\n")
    conv = mod.ops["convolution.1"]
    # 3*3*2 MACs per output element (kernel elems / out features)
    assert afusion.op_flops(conv) == 2 * (8 * 8 * 4) * (3 * 3 * 2)


def test_flop_model_fusion_sums_body():
    mod = parse_hlo(_NESTED_HLO)
    fop = mod.ops["tanh_add_fusion"]
    # tanh + 2 converts + add, 64*64 elements each
    assert afusion.op_flops(fop, mod) == 4 * 64 * 64


# ---------------------------------------------------------------------------
# ideal-fusion diff: golden known-bad programs
# ---------------------------------------------------------------------------

def _stranded_hlo(transposed=True):
    """Two loop fusions with a transpose (known-bad) or a direct edge
    (known-good twin) between them."""
    mid = ("  %transpose.7 = f32[512,512]{1,0} transpose(f32[512,512]"
           "{1,0} %scale_fusion), dimensions={1,0}\n"
           if transposed else "")
    feed = "%transpose.7" if transposed else "%scale_fusion"
    return textwrap.dedent("""\
    HloModule jit_bad, is_scheduled=true, entry_computation_layout={(f32[512,512]{1,0})->f32[512,512]{1,0}}

    %fused_computation (param_0.1: f32[512,512]) -> f32[512,512] {
      %param_0.1 = f32[512,512]{1,0} parameter(0)
      %constant.1 = f32[] constant(2)
      %broadcast.1 = f32[512,512]{1,0} broadcast(f32[] %constant.1), dimensions={}
      ROOT %multiply.1 = f32[512,512]{1,0} multiply(f32[512,512]{1,0} %param_0.1, f32[512,512]{1,0} %broadcast.1)
    }

    %fused_computation.1 (param_0.2: f32[512,512]) -> f32[512,512] {
      %param_0.2 = f32[512,512]{1,0} parameter(0)
      %tanh.1 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} %param_0.2)
      ROOT %add.1 = f32[512,512]{1,0} add(f32[512,512]{1,0} %tanh.1, f32[512,512]{1,0} %param_0.2)
    }

    ENTRY %main.1 (p0: f32[512,512]) -> f32[512,512] {
      %p0 = f32[512,512]{1,0} parameter(0)
      %scale_fusion = f32[512,512]{1,0} fusion(f32[512,512]{1,0} %p0), kind=kLoop, calls=%fused_computation
    """) + mid + (
        "  ROOT %tanh_add_fusion = f32[512,512]{1,0} fusion(f32[512,512]"
        "{1,0} " + feed + "), kind=kLoop, calls=%fused_computation.1\n"
        "}\n")


def test_known_bad_stranded_transpose_between_fusions():
    report = afusion.fusion_census(_stranded_hlo(True))
    assert len(report.stranded) == 1
    s = report.stranded[0]
    assert s.opcode == "transpose" and s.bytes == 512 * 512 * 4
    assert s.producer == "scale_fusion"
    assert s.consumers == ["tanh_add_fusion"]
    assert any(f.rule == "stranded-op" for f in report.findings)
    # known-good twin: direct fusion->fusion edge, nothing stranded
    clean = afusion.fusion_census(_stranded_hlo(False))
    assert clean.stranded == []
    assert not any(f.rule == "stranded-op" for f in clean.findings)


def test_stranded_floor_suppresses_scalar_glue():
    report = afusion.fusion_census(_stranded_hlo(True),
                                   stranded_floor_bytes=512 * 512 * 4 + 1)
    assert report.stranded == []


_BIG_BOUNDARY_HLO = textwrap.dedent("""\
HloModule jit_big, is_scheduled=true, entry_computation_layout={(f32[2048,2048]{1,0})->f32[2048,2048]{1,0}}

%fused_computation (param_0.1: f32[2048,2048]) -> f32[2048,2048] {
  %param_0.1 = f32[2048,2048]{1,0} parameter(0)
  ROOT %exp.1 = f32[2048,2048]{1,0} exponential(f32[2048,2048]{1,0} %param_0.1)
}

%fused_computation.1 (param_0.2: f32[2048,2048], param_1.2: f32[2048,2048]) -> f32[2048,2048] {
  %param_0.2 = f32[2048,2048]{1,0} parameter(0)
  %param_1.2 = f32[2048,2048]{1,0} parameter(1)
  ROOT %add.1 = f32[2048,2048]{1,0} add(f32[2048,2048]{1,0} %param_0.2, f32[2048,2048]{1,0} %param_1.2)
}

ENTRY %main.1 (p0: f32[2048,2048]) -> f32[2048,2048] {
  %p0 = f32[2048,2048]{1,0} parameter(0)
  %dot.1 = f32[2048,2048]{1,0} dot(f32[2048,2048]{1,0} %p0, f32[2048,2048]{1,0} %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %exp_fusion = f32[2048,2048]{1,0} fusion(f32[2048,2048]{1,0} %dot.1), kind=kLoop, calls=%fused_computation
  ROOT %add_fusion = f32[2048,2048]{1,0} fusion(f32[2048,2048]{1,0} %exp_fusion, f32[2048,2048]{1,0} %dot.1), kind=kOutput, calls=%fused_computation.1
}
""")


def test_known_bad_large_boundary_materialization():
    report = afusion.fusion_census(_BIG_BOUNDARY_HLO)
    # ranked: the 16 MiB dot output (2 consumers) first
    assert report.boundaries[0].name == "dot.1"
    assert report.boundaries[0].bytes == 2048 * 2048 * 4
    assert report.boundary_bytes == 2 * 2048 * 2048 * 4
    bf = [f for f in report.findings if f.rule == "fusion-boundary"]
    assert bf and "dot.1" in bf[0].where
    # fusion kinds parsed: one kLoop + one kOutput
    assert report.by_kind() == {"dot": 1, "loop": 1, "output": 1}


def test_bound_classification_against_ridge():
    report = afusion.fusion_census(_BIG_BOUNDARY_HLO)
    dot = [k for k in report.kernels if k.kind == "dot"][0]
    # 2048^3 matmul: intensity ~341 flop/byte, above the ~180 ridge
    assert dot.bound() == "compute"
    loop = [k for k in report.kernels if k.kind == "loop"][0]
    assert loop.bound() == "memory"
    # flop-weighted: the dot dominates
    assert report.compute_bound_pct > 99.0
    # ridge override flips the classification
    assert dot.bound(ridge=1e9) == "memory"


def test_report_roundtrips_to_dict():
    report = afusion.fusion_census(_BIG_BOUNDARY_HLO)
    d = report.to_dict()
    assert d["n_fusions"] == 2 and d["n_kernels"] == 3
    assert d["boundary_bytes"] == report.boundary_bytes
    assert d["kernels"][0]["bound"] in ("compute", "memory")
    brief = report.brief()
    assert set(brief) == {"n_fusions", "stranded_ops", "boundary_bytes",
                          "compute_bound_pct"}
    assert "fusions=2" in report.summary_line()
    assert "dot.1" in report.table()


# ---------------------------------------------------------------------------
# fused-body visibility for the other HLO scans (satellite)
# ---------------------------------------------------------------------------

def test_dtype_drift_hlo_fallback_sees_inside_fusions():
    """A widening f32->f64 convert XLA pulled into a fusion body: the
    jaxpr-less scan must find it and name the kernel it hides in."""
    findings = dtype_drift_scan(None, hlo_text=_NESTED_HLO)
    wide = [f for f in findings if "float64" in f.message]
    assert len(wide) == 1
    assert wide[0].severity == "error"
    assert "inside fusion %tanh_add_fusion" in wide[0].where
    # the f64->f32 narrowing twin is free: not flagged
    assert all("float64 -> float32" not in f.message for f in findings)


def test_host_transfer_scan_attributes_fusion_body():
    hlo = textwrap.dedent("""\
    HloModule jit_leak, is_scheduled=true, entry_computation_layout={(f32[8]{0})->f32[8]{0}}

    %fused_computation (param_0.1: f32[8]) -> f32[8] {
      %param_0.1 = f32[8]{0} parameter(0)
      ROOT %custom-call.1 = f32[8]{0} custom-call(f32[8]{0} %param_0.1), custom_call_target="xla_python_cpu_callback"
    }

    ENTRY %main.1 (p0: f32[8]) -> f32[8] {
      %p0 = f32[8]{0} parameter(0)
      ROOT %cb_fusion = f32[8]{0} fusion(f32[8]{0} %p0), kind=kCustom, calls=%fused_computation
    }
    """)
    findings = host_transfer_scan(None, hlo)
    assert len(findings) == 1
    assert "inside fusion %cb_fusion" in findings[0].where


# ---------------------------------------------------------------------------
# expect_mode fusion pack
# ---------------------------------------------------------------------------

def test_expect_mode_escalates_stranded_ops():
    report = ProgramReport(mode="fused")
    report.fusion = afusion.fusion_census(_stranded_hlo(True))
    expect_mode(report, mode="fused")
    errs = [f for f in report.findings
            if f.rule == "stranded-op" and f.severity == "error"]
    assert len(errs) == 1 and "transpose" in errs[0].message
    assert not report.ok
    # clean program: no escalation
    clean = ProgramReport(mode="fused")
    clean.fusion = afusion.fusion_census(_stranded_hlo(False))
    expect_mode(clean, mode="fused")
    assert clean.ok


# ---------------------------------------------------------------------------
# baseline regression gate
# ---------------------------------------------------------------------------

def _report_for(n_fusions=10, stranded=0, boundary=1000):
    rep = afusion.FusionReport(boundary_bytes=boundary)
    for i in range(n_fusions):
        rep.kernels.append(afusion.FusionKernel(
            name=f"f{i}", kind="loop", computation="main", n_ops=2,
            op_census={"add": 2}, flops=10, bytes_in=8, bytes_out=8))
    for i in range(stranded):
        rep.stranded.append(afusion.StrandedOp(
            name=f"s{i}", opcode="transpose", bytes=8192,
            producer="f0", consumers=["f1"], computation="main"))
    return rep


def test_baseline_gate_passes_in_band():
    base = {"leg": {"n_fusions": 10, "stranded_ops": 0,
                    "boundary_bytes": 1000, "tol_pct": 25}}
    assert afusion.check_baseline(_report_for(), base, "leg") == []
    # within band: 12 fusions (band = 10 +- max(1, 2.5) = +-3 -> 2)
    assert afusion.check_baseline(_report_for(n_fusions=12), base,
                                  "leg") == []
    # fewer boundary bytes is an improvement, not a violation
    assert afusion.check_baseline(_report_for(boundary=100), base,
                                  "leg") == []


def test_baseline_gate_flags_regressions():
    base = {"leg": {"n_fusions": 10, "stranded_ops": 0,
                    "boundary_bytes": 1000, "tol_pct": 25}}
    # fusion count left the band (either direction)
    bad = afusion.check_baseline(_report_for(n_fusions=20), base, "leg")
    assert [f.rule for f in bad] == ["fusion-regression"]
    assert all(f.severity == "error" for f in bad)
    bad = afusion.check_baseline(_report_for(n_fusions=2), base, "leg")
    assert [f.rule for f in bad] == ["fusion-regression"]
    # new stranded op
    bad = afusion.check_baseline(_report_for(stranded=1), base, "leg")
    assert len(bad) == 1 and "stranded" in bad[0].message
    # boundary bytes beyond +tol
    bad = afusion.check_baseline(_report_for(boundary=1500), base, "leg")
    assert len(bad) == 1 and "boundary" in bad[0].message
    # unknown leg: warn, not error (the gate must not invent baselines)
    miss = afusion.check_baseline(_report_for(), base, "other")
    assert len(miss) == 1 and miss[0].severity == "warn"


def test_baseline_from_env(monkeypatch, tmp_path):
    monkeypatch.delenv("MXNET_FUSION_BASELINE", raising=False)
    assert afusion.baseline_from_env() is None
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"mlp": {"n_fusions": 5}}))
    monkeypatch.setenv("MXNET_FUSION_BASELINE", str(p))
    baselines, leg = afusion.baseline_from_env()
    assert baselines == {"mlp": {"n_fusions": 5}} and leg is None
    monkeypatch.setenv("MXNET_FUSION_BASELINE", f"{p}:mlp")
    baselines, leg = afusion.baseline_from_env()
    assert leg == "mlp"


# ---------------------------------------------------------------------------
# real compiled programs (the ISSUE 9 acceptance path)
# ---------------------------------------------------------------------------

def _mlp_leg():
    onp.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(onp.random.randn(8, 8).astype("float32"))
    y = mx.nd.array(onp.random.randint(0, 4, size=(8,)).astype("int32"))
    net(x)
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore=None)
    step = trainer.compile_step(lambda a, b: loss_blk(net(a), b))
    return step, x, y


class _WordLM(mx.gluon.HybridBlock):
    """examples/train_lstm_lm.py's architecture at tiny dims — the
    worst-MFU BENCH leg's shape (Embedding -> fused LSTM -> Dense)."""

    def __init__(self, vocab, embed, hidden):
        super().__init__()
        self.emb = nn.Embedding(vocab, embed)
        self.lstm = rnn.LSTM(hidden, num_layers=1, layout="NTC")
        self.head = nn.Dense(vocab, flatten=False)

    def forward(self, tokens):
        return self.head(self.lstm(self.emb(tokens)))


def _lstm_leg():
    onp.random.seed(0)
    vocab = 16
    lm = _WordLM(vocab, 8, 16)
    lm.initialize()
    x = mx.nd.array(onp.random.randint(0, vocab, size=(4, 8))
                    .astype("int32"))
    y = mx.nd.array(onp.random.randint(0, vocab, size=(4, 8))
                    .astype("int32"))
    lm(x)
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(lm.collect_params(), "adam",
                      {"learning_rate": 5e-3}, kvstore=None)
    step = trainer.compile_step(lambda a, b: loss_blk(lm(a), b))
    return step, x, y


def test_analyze_populates_fusion_report():
    step, x, y = _mlp_leg()
    step(x, y)
    report = step.analyze(x, y)
    fr = report.fusion
    assert fr is not None and fr.n_fusions > 0
    assert fr.stranded == []          # the fused MLP step is clean
    assert fr.boundary_bytes > 0
    assert report.ok, report.summary()
    assert report.to_dict()["fusion"]["n_fusions"] == fr.n_fusions
    assert "fusion" in report.summary()
    # fusion_report() is the cached census off the same bucket
    assert step.fusion_report(x, y) is fr


def test_fusion_gauges_published():
    step, x, y = _mlp_leg()
    step(x, y)
    fr = step.fusion_report(x, y)
    assert telemetry.value(telemetry.names.FUSION_REGIONS) \
        == fr.n_fusions
    assert telemetry.value(telemetry.names.FUSION_BOUNDARY_BYTES) \
        == fr.boundary_bytes
    assert telemetry.value(telemetry.names.FUSION_STRANDED) == 0


def test_fusion_report_none_on_eager():
    onp.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.array(onp.random.randn(8, 8).astype("float32"))
    y = mx.nd.array(onp.random.randint(0, 4, size=(8,)).astype("int32"))
    net(x)
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=None)

    def hostile(a, b):
        out = net(a)
        _ = out.asnumpy().sum()          # demotes the step to eager
        return loss_blk(out, b)

    estep = tr.compile_step(hostile)
    estep(x, y)
    assert estep.mode == "eager"
    assert estep.fusion_report(x, y) is None


def test_analyze_raise_enforces_injected_baseline(monkeypatch, tmp_path):
    """The gate wired through compile_step(analyze='raise'): a baseline
    that demands far fewer fusions than the program has must fail the
    first step with a fusion-regression error."""
    p = tmp_path / "tight.json"
    p.write_text(json.dumps(
        {"mlp": {"n_fusions": 1, "stranded_ops": 0,
                 "boundary_bytes": 1, "tol_pct": 0}}))
    monkeypatch.setenv("MXNET_FUSION_BASELINE", f"{p}:mlp")
    onp.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(onp.random.randn(8, 8).astype("float32"))
    y = mx.nd.array(onp.random.randint(0, 4, size=(8,)).astype("int32"))
    net(x)
    loss_blk = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore=None)
    rstep = trainer.compile_step(lambda a, b: loss_blk(net(a), b),
                                 analyze="raise")
    with pytest.raises(MXNetError, match="fusion"):
        rstep(x, y)


def test_analyze_passes_on_checked_in_baseline(monkeypatch):
    monkeypatch.setenv("MXNET_FUSION_BASELINE", f"{BASELINES}:mlp")
    step, x, y = _mlp_leg()
    step(x, y)
    report = step.analyze(x, y)
    assert not [f for f in report.findings
                if f.rule == "fusion-regression"], report.summary()
    assert report.ok


# ---------------------------------------------------------------------------
# MXA005: unrolled-loop source lint
# ---------------------------------------------------------------------------

def _lint(body: str):
    src = ("class B:\n"
           "    def forward(self, x, mask=None):\n"
           + textwrap.indent(textwrap.dedent(body), "        "))
    return lint_source(src, filename="snippet.py")


def test_mxa005_flags_shape_derived_range():
    fs = _lint("outs = []\n"
               "for i in range(x.shape[0]):\n"
               "    outs.append(x * i)\n"
               "return outs\n")
    assert [f.rule for f in fs] == ["MXA005"]
    assert "unroll" in fs[0].message and fs[0].severity == "warn"


def test_mxa005_flags_iterating_traced_array():
    fs = _lint("acc = x * 0\nfor row in x:\n    acc = acc + row\n"
               "return acc\n")
    assert "MXA005" in [f.rule for f in fs]


def test_mxa005_skips_literal_and_non_tensor_loops():
    # literal range: visibly small and static
    assert _lint("for i in range(3):\n    x = x + i\nreturn x\n") == []
    # dynamic range but no tensor work in the body
    assert _lint("n = 0\nfor i in range(self.depth):\n    n += i\n"
                 "return x\n") == []


def test_mxa005_inline_allow_blesses():
    fs = _lint("for i in range(x.shape[0]):  # mx-lint: allow=MXA005\n"
               "    x = x + i\nreturn x\n")
    assert len(fs) == 1 and fs[0].blessed


def test_mxa005_scans_unroll_methods_only_for_unrolling():
    """``unroll`` methods are scanned for MXA005 but NOT the other
    rules — their config-flag args would false-flag MXA003."""
    src = textwrap.dedent("""\
    class Cell:
        def unroll(self, length, inputs, merge_outputs=None):
            if merge_outputs:
                inputs = inputs * 1
            outs = []
            for i in range(length):
                outs.append(inputs * i)
            return outs
    """)
    fs = lint_source(src, filename="cell.py")
    assert [f.rule for f in fs] == ["MXA005"]


def test_mxa005_fires_on_the_reference_unroller(lint_allowlist):
    """The known-present sentinel: RecurrentCell.unroll IS a Python
    unroller and must keep firing MXA005 (blessed in the allowlist) —
    if it vanishes, the rule or the blessing is stale."""
    from mxnet_tpu.analysis.lint import filter_allowed, lint_path
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = lint_path(os.path.join(repo, "mxnet_tpu", "gluon", "rnn"))
    hits = [f for f in findings if f.rule == "MXA005"]
    assert hits, "RecurrentCell.unroll no longer fires MXA005"
    assert filter_allowed(hits, lint_allowlist) == [], \
        "rnn unroller MXA005 findings must be blessed in the allowlist"


# ---------------------------------------------------------------------------
# tier-1 baseline sweep (lint-marked, like the source-lint sweep)
# ---------------------------------------------------------------------------

@pytest.mark.lint
@pytest.mark.parametrize("leg,builder", [("mlp", _mlp_leg),
                                         ("lstm", _lstm_leg)])
def test_fusion_baseline_sweep(leg, builder):
    """The regression gate over the checked-in baselines: each leg's
    compiled program must hold its fusion posture (count band, zero new
    stranded ops, boundary bytes within tolerance). A jax bump that
    legitimately shifts these fails HERE — refresh the fixture in the
    same PR with the diff explained (docs/ANALYSIS.md)."""
    step, x, y = builder()
    step(x, y)
    fr = step.fusion_report(x, y)
    assert fr is not None and fr.n_fusions > 0, \
        f"[{leg}] no fusion census for a compiled step"
    baselines = afusion.load_baselines(BASELINES)
    findings = afusion.check_baseline(fr, baselines, leg)
    assert findings == [], (
        f"[{leg}] fusion posture regressed vs "
        f"tests/fixtures/fusion_baselines.json "
        f"(measured: {fr.brief()}):\n"
        + "\n".join(f"  {f}" for f in findings))


@pytest.mark.lint
def test_fusion_baseline_sweep_lstm_kernel(monkeypatch):
    """The lstm leg compiled with the Pallas kernel layer forced to
    its interpret tier (MXNET_PALLAS=on): the kernel-path program is
    gated by its own checked-in baseline so a regression in the
    kernelized program fails tier-1 just like the XLA path. (The raw
    interpret-mode boundary_bytes are NOT comparable to the XLA leg's
    — the interpret harness carries whole buffers through its grid
    while-loops; the kernel's actual HBM win is pinned as the strict
    backward-residual ratchet in tests/test_kernels.py.)"""
    monkeypatch.setenv("MXNET_PALLAS", "on")
    step, x, y = _lstm_leg()
    step(x, y)
    fr = step.fusion_report(x, y)
    assert fr is not None and fr.n_fusions > 0
    baselines = afusion.load_baselines(BASELINES)
    findings = afusion.check_baseline(fr, baselines, "lstm_kernel")
    assert findings == [], (
        f"[lstm_kernel] fusion posture regressed "
        f"(measured: {fr.brief()}):\n"
        + "\n".join(f"  {f}" for f in findings))


# ---------------------------------------------------------------------------
# custom-call FLOP estimators (PR 10 satellite: kernel legs stop
# under-counting in the bound classification)
# ---------------------------------------------------------------------------

_CUSTOM_CALL_HLO = """\
HloModule cc_test

ENTRY %main {
  %p0 = f32[16,512,64]{2,1,0} parameter(0)
  %p1 = f32[16,512,64]{2,1,0} parameter(1)
  %p2 = f32[16,512,64]{2,1,0} parameter(2)
  %cc = f32[16,512,64]{2,1,0} custom-call(%p0, %p1, %p2), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/flash_fwd/_flash_kernel"}
  %xw = f32[8,4,512]{2,1,0} parameter(3)
  %wh = f32[512,128]{1,0} parameter(4)
  %sc = f32[8,4,128]{2,1,0} custom-call(%xw, %wh), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/rnn/_fwd_kernel"}
  %un = f32[16,512,64]{2,1,0} custom-call(%p0), custom_call_target="SomeUnknownTarget"
  ROOT %t = (f32[16,512,64]{2,1,0}, f32[8,4,128]{2,1,0}, f32[16,512,64]{2,1,0}) tuple(%cc, %sc, %un)
}
"""


def test_custom_call_flops_builtin_estimators():
    """Flash-attention and rnn-scan custom calls get real FLOP
    estimates (matched on the kernel function name in the op_name
    metadata); unknown custom calls stay at 0 — compute_bound_pct no
    longer under-counts kernel legs."""
    fr = afusion.fusion_census(_CUSTOM_CALL_HLO)
    by_name = {k.name: k for k in fr.kernels}
    assert by_name["cc"].flops == 4 * 16 * 512 * 512 * 64
    assert by_name["cc"].bound() == "compute"
    assert by_name["sc"].flops == 2 * 8 * 4 * 512 * 128 \
        + 10 * 8 * 4 * 512
    assert by_name["un"].flops == 0
    assert fr.compute_bound_pct > 0


def test_register_custom_call_flops_hook():
    """The public hook: a registered estimator applies by substring
    match, re-registering a name replaces it, and an estimator that
    raises degrades to 0 (a census must never die)."""
    from mxnet_tpu.analysis.hlo import parse_hlo
    mod = parse_hlo(_CUSTOM_CALL_HLO)
    op = mod.ops["un"]
    try:
        afusion.register_custom_call_flops(
            "my_kernel", lambda op, mod=None: 1234,
            match="someunknowntarget")
        assert afusion.op_flops(op, mod) == 1234
        afusion.register_custom_call_flops(
            "my_kernel", lambda op, mod=None: 5678,
            match="someunknowntarget")
        assert afusion.op_flops(op, mod) == 5678
        afusion.register_custom_call_flops(
            "my_kernel", lambda op, mod=None: 1 / 0,
            match="someunknowntarget")
        assert afusion.op_flops(op, mod) == 0
    finally:
        afusion._CUSTOM_CALL_FLOPS[:] = [
            e for e in afusion._CUSTOM_CALL_FLOPS
            if e[0] != "my_kernel"]
