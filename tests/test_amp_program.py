"""AMP program-level dtype regression tests (VERDICT r3 weak #3).

Rounds 1-2 shipped an AMP that silently no-opped: the op lists held
CamelCase names while the invoke funnel registers snake_case, so no MXU
op ever matched and "bf16" ran f32-width activations. These tests make
that class of drift impossible to reintroduce:

1. inspect the ACTUAL traced program (jaxpr) of a hybridized conv block
   under ``amp.init()`` and assert the conv/matmul ops compute in
   bfloat16 (activation HBM width — the thing AMP exists to halve);
2. assert every name in the AMP op lists matches a real invoke-funnel
   call site in the source tree (the sanity check whose absence hid the
   CamelCase mismatch for two rounds);
3. demonstrate the probe catches the historical bug: with the round-1
   CamelCase lists patched in, the same trace shows f32 convs.

Reference analog: the dtype-flow assertions of
tests/python/unittest/test_contrib_amp.py, strengthened to the compiled
program level.
"""
import os
import re

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
import mxnet_tpu.amp as amp_mod
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray.ndarray import NDArray


def _make_net():
    onp.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.Flatten(),
            nn.Dense(10))
    net.initialize()
    x = nd.array(onp.random.randn(2, 3, 8, 8).astype("float32"))
    net(x)  # materialize deferred shapes (pre-AMP, like bench.py)
    return net, x


def _trace_forward(net, x):
    """jaxpr of the block's forward — the program jit would compile."""
    params = [p for p in net.collect_params().values()
              if p._data is not None]

    def fn(xd, pd):
        orig = [p._data for p in params]
        for p, d in zip(params, pd):
            p._data = NDArray(d)
        try:
            out = net.forward(NDArray(xd))
        finally:
            for p, o in zip(params, orig):
                p._data = o
        return out._data

    return jax.make_jaxpr(fn)(x._data,
                              tuple(p._data._data for p in params))


def _eqn_out_dtypes(jaxpr, prim_name):
    out = []
    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == prim_name:
                out.extend(v.aval.dtype for v in eqn.outvars)
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)
    walk(jaxpr.jaxpr)
    return [str(d) for d in out]


def test_amp_program_runs_conv_and_dense_in_bf16():
    net, x = _make_net()
    amp_mod.init()
    try:
        jx = _trace_forward(net, x)
    finally:
        amp_mod.uninit()
    convs = _eqn_out_dtypes(jx, "conv_general_dilated")
    dots = _eqn_out_dtypes(jx, "dot_general")
    assert convs, "no conv in traced program — probe is broken"
    assert dots, "no matmul in traced program — probe is broken"
    assert all(d == "bfloat16" for d in convs), convs
    assert all(d == "bfloat16" for d in dots), dots


def test_amp_off_program_is_f32():
    net, x = _make_net()
    jx = _trace_forward(net, x)
    convs = _eqn_out_dtypes(jx, "conv_general_dilated")
    assert convs and all(d == "float32" for d in convs), convs


def test_round1_camelcase_lists_would_now_fail(monkeypatch):
    """With the historical (broken) CamelCase lists, the probe must see
    f32 convs — i.e. this regression test would have caught the bug."""
    monkeypatch.setattr(amp_mod, "TARGET_DTYPE_OPS",
                        {"Convolution", "FullyConnected", "Dot"})
    net, x = _make_net()
    amp_mod.init()
    try:
        jx = _trace_forward(net, x)
    finally:
        amp_mod.uninit()
    convs = _eqn_out_dtypes(jx, "conv_general_dilated")
    assert convs and all(d == "float32" for d in convs), \
        "CamelCase lists unexpectedly matched the invoke funnel"


def test_amp_fp32_ops_cast_up():
    """softmax under AMP computes in f32 even when bf16 flows in."""
    amp_mod.init()
    try:
        y = nd.softmax(nd.ones((2, 4)).astype("bfloat16"))
    finally:
        amp_mod.uninit()
    assert str(y.dtype) in ("float32",)


def test_amp_list_names_match_invoke_funnel():
    """Every AMP list entry must name a real invoke-funnel call site.
    Scans the source for invoke_raw("<name>" occurrences; a drift like
    round 1's CamelCase entries fails here immediately."""
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu")
    names = set()
    pat = re.compile(r'invoke_raw\(\s*f?"([A-Za-z0-9_{}]+)"')
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            if f.endswith(".py"):
                with open(os.path.join(dirpath, f), encoding="utf8") as fh:
                    names.update(pat.findall(fh.read()))
    # f-string sites like "rnn_{mode}" register a prefix family
    prefixes = tuple(n.split("{")[0] for n in names if "{" in n)
    names = {n for n in names if "{" not in n}

    def known(op):
        return op in names or (prefixes and op.startswith(prefixes))

    missing = [op for op in amp_mod.TARGET_DTYPE_OPS if not known(op)]
    assert not missing, f"TARGET_DTYPE_OPS entries with no invoke site: " \
                        f"{missing}"
    missing = [op for op in amp_mod.FP32_OPS if not known(op)]
    assert not missing, f"FP32_OPS entries with no invoke site: {missing}"
