#!/usr/bin/env python
"""Per-conv-shape XLA cost probe: for every distinct convolution in the
ResNet-50 forward, compile THAT conv alone and compare XLA's counted
flops against the algebraic 2*N*C_in*K_h*K_w per output element — the
microscope for the program-level executed-vs-analytic multiplier
(benchmark/flops_attrib.py).

Usage: python benchmark/conv_cost_probe.py [bs]
Appends results to benchmark/flops_attrib.json under "conv_probe".
"""
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp
import jax
import jax.numpy as jnp
from jax import lax


def parse_stablehlo_convs(txt):
    """(lhs, rhs, out, strides, padding) for every stablehlo.convolution."""
    convs = []
    # stablehlo.convolution(%a, %b) ... {stride = [2, 2], pad = [[3, 3], [3, 3]]} ...
    #   : (tensor<128x3x224x224xbf16>, tensor<64x3x7x7xbf16>) -> tensor<...>
    pat = re.compile(
        r"stablehlo\.convolution.*?window = \{([^}]*)\}.*?"
        r":\s*\(tensor<([^>]+)>,\s*tensor<([^>]+)>\)\s*->\s*tensor<([^>]+)>")
    for m in pat.finditer(txt):
        win, lhs, rhs, out = m.groups()
        sm = re.search(r"stride = \[([\d, ]+)\]", win)
        stride = tuple(int(x) for x in sm.group(1).split(",")) if sm \
            else (1, 1)
        pm = re.search(r"pad = \[\[(\d+), (\d+)\], \[(\d+), (\d+)\]\]", win)
        pad = tuple(int(x) for x in pm.groups()) if pm else (0, 0, 0, 0)

        def dims(s):
            parts = s.split("x")
            return tuple(int(p) for p in parts[:-1]), parts[-1]
        convs.append({"lhs": dims(lhs), "rhs": dims(rhs),
                      "out": dims(out), "stride": stride, "pad": pad})
    return convs


def algebra_gflops(c):
    (n, ci, h, w), _ = c["lhs"]
    (co, cig, kh, kw), _ = c["rhs"]
    (no, coo, ho, wo), _ = c["out"]
    return 2.0 * no * coo * ho * wo * cig * kh * kw / 1e9


def probe_xla_flops(c):
    (n, ci, h, w), ldt = c["lhs"]
    (co, cig, kh, kw), rdt = c["rhs"]
    dt = jnp.bfloat16 if "bf16" in ldt else jnp.float32
    a = jnp.zeros((n, ci, h, w), dt)
    b = jnp.zeros((co, cig, kh, kw), dt)
    pad = c["pad"]

    def f(a, b):
        return lax.conv_general_dilated(
            a, b, window_strides=c["stride"],
            padding=((pad[0], pad[1]), (pad[2], pad[3])),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=ci // cig)

    comp = jax.jit(f).lower(a, b).compile()
    ca = comp.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    return float(ca.get("flops", 0.0)) / 1e9


def main():
    bs = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from __graft_entry__ import _init_net, _functional_apply

    onp.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    params = _init_net(net, (1, 3, 224, 224))
    mx.amp.init()
    try:
        pd = tuple(jnp.array(p._data._data, copy=True) for p in params)
        x = jnp.asarray(onp.random.uniform(
            size=(bs, 3, 224, 224)).astype("float32"))
        key = jax.random.PRNGKey(0)
        fwd = _functional_apply(net, params, train=False)
        txt = jax.jit(fwd).lower(pd, x, key).as_text()
    finally:
        mx.amp.uninit()

    convs = parse_stablehlo_convs(txt)
    print(f"{len(convs)} convolution sites in the forward", flush=True)
    # dedup by full config
    seen = {}
    for c in convs:
        k = json.dumps({k2: v for k2, v in c.items()}, sort_keys=True)
        seen.setdefault(k, {"cfg": c, "n": 0})
        seen[k]["n"] += 1

    rows = []
    tot_alg = tot_xla = 0.0
    for e in seen.values():
        c, n = e["cfg"], e["n"]
        alg = algebra_gflops(c)
        xla = probe_xla_flops(c)
        rows.append({"lhs": c["lhs"][0], "rhs": c["rhs"][0],
                     "out": c["out"][0], "stride": c["stride"], "n": n,
                     "algebra_gflops": alg, "xla_gflops": xla,
                     "ratio": xla / alg if alg else None})
        tot_alg += n * alg
        tot_xla += n * xla
        print(f"n={n:2d} lhs={str(c['lhs'][0]):22s} rhs={str(c['rhs'][0]):20s}"
              f" alg={alg:7.2f}G xla={xla:8.2f}G ratio={xla/alg:5.2f}",
              flush=True)
    print(f"TOTAL fwd conv: algebra={tot_alg:.1f}G xla_single_op_sum="
          f"{tot_xla:.1f}G ratio={tot_xla/tot_alg:.3f}")

    path = "benchmark/flops_attrib.json"
    data = json.load(open(path)) if os.path.exists(path) else {}
    data["conv_probe"] = {"bs": bs, "rows": rows,
                          "total_algebra_gflops": tot_alg,
                          "total_xla_gflops": tot_xla,
                          "ratio": tot_xla / tot_alg}
    json.dump(data, open(path, "w"), indent=1)
    print("updated", path)


if __name__ == "__main__":
    main()
