#!/usr/bin/env python
"""Chip-level per-op microbenchmark for tunneled TPU platforms.

`opbench.py`'s eager per-call loop is the CPU regression tool; through
the axon tunnel it measures dispatch (sync host-fetch ~860 ms/op, fully
pipelined floor ~37 ms/op), not the chip. This harness gets honest chip
numbers by running each op chained inside ONE compiled `lax.fori_loop`
— the tunnel is paid twice per measurement (dispatch + final fetch) and
its constant cost is eliminated by timing the loop at two iteration
counts and taking the slope.

Chaining strategies (XLA must not be able to hoist or CSE the body):
- matmul/FC: the output feeds back as the next input (roofline style),
  with an rsqrt(mean-square) renormalization so values never overflow.
- conv: a scalar derived from the output perturbs the *weights* (cheap:
  weights are KBs, activations are MBs) — data-dependent, so XLA cannot
  constant-fold it even though the perturbation is numerically ~0.
- elementwise/BN: output shape == input shape, direct feedback.

Ops are invoked through the framework's own nd API (they trace under
jit exactly as Gluon's CachedOp traces them), so a regression in the
invoke funnel or kernel emitters shows up here.

Case set = the shapes that carry ResNet-50 bs=128 and BERT-base bs=32
(the two bench.py models), per docs/PERF_NOTES.md MFU attribution.
Reference analog: benchmark/opperf per-op sweeps (reference
benchmark/opperf/opperf.py), re-targeted at what a TPU cares about.

Run: python benchmark/opbench_tpu.py [--n1 20] [--reps 3]
(the second iteration count is chosen adaptively per case). Writes one
JSON line per case; commit output as benchmark/opbench.tpu.json.
"""
import argparse
import functools
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    # honor the env override even where a sitecustomize pre-imported jax
    # pinned to an accelerator platform (axon images)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
from jax import lax


def _flush(c):
    leaf = jax.tree_util.tree_leaves(c)[0]
    return float(jnp.reshape(leaf, (-1,))[0].astype(jnp.float32))


def _time_loop(body, init, n1, reps, target_delta=2.0, n_cap=20000):
    """Seconds per iteration of `body`, tunnel-overhead-free: time the
    compiled fori_loop at two iteration counts, slope = (t2-t1)/(n2-n1).

    n2 is adaptive: the tunnel's round-trip jitter is O(100 ms), so the
    iteration delta must represent >= `target_delta` seconds of on-chip
    work or the slope is noise (first cut with a fixed n2=120 measured a
    4096 matmul at 205 TFLOP/s — above the chip's 197 peak)."""
    f1 = jax.jit(lambda c: lax.fori_loop(0, n1, body, c))
    _flush(f1(init))  # compile + warm
    t0 = time.perf_counter()
    _flush(f1(init))
    t_n1 = time.perf_counter() - t0
    # estimate overhead with an n=1 loop (same compile shape, 1 iter)
    g1 = jax.jit(lambda c: lax.fori_loop(0, 1, body, c))
    _flush(g1(init))
    t0 = time.perf_counter()
    _flush(g1(init))
    t_ovh = time.perf_counter() - t0
    est_iter = max((t_n1 - t_ovh) / max(n1 - 1, 1), 1e-7)
    n2 = n1 + min(int(target_delta / est_iter) + 1, n_cap)
    f2 = jax.jit(lambda c: lax.fori_loop(0, n2, body, c))
    _flush(f2(init))  # compile + warm
    slopes = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _flush(f1(init))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        _flush(f2(init))
        t2 = time.perf_counter() - t0
        slopes.append((t2 - t1) / (n2 - n1))
    return float(onp.median(slopes))


def _nd(x):
    from mxnet_tpu.ndarray.ndarray import NDArray
    return NDArray(x)


def _renorm(y):
    return y * lax.rsqrt(jnp.mean(jnp.square(y.astype(jnp.float32))) +
                         1e-6).astype(y.dtype)


def _cases(rng):
    """[(name, build)] where build() -> (init_carry, body(i, c) -> c,
    flops_per_iter, bytes_per_iter). Lazy: device arrays materialize only
    for selected cases (each transfer is a tunnel round trip)."""
    from mxnet_tpu import nd

    cases = []

    def arr(shape, dtype):
        return jnp.asarray(rng.randn(*shape).astype("float32")).astype(dtype)

    # ---- MXU: square matmuls (the roofline the model competes against)
    def make_matmul(n, dt):
        def build():
            a = arr((n, n), dt)

            def body(i, c):
                return _renorm(nd.dot(_nd(a), _nd(c))._data)

            return a, body, 2 * n ** 3, None
        return build

    for n, dt in ((4096, "bfloat16"), (8192, "bfloat16"), (4096, "float32")):
        cases.append((f"matmul_{n}_{dt}", make_matmul(n, dt)))

    # ---- ResNet-50 bs=128 conv shapes (NCHW API; bf16 as AMP runs them)
    B = 128

    def make_conv(ci, co, hw, k, s, p):
        def build():
            x = arr((B, ci, hw, hw), "bfloat16")
            w = arr((co, ci, k, k), "bfloat16")
            ho = hw // s

            def body(i, c):
                weff = w + c.astype(w.dtype)
                y = nd.Convolution(_nd(x), _nd(weff), kernel=(k, k),
                                   stride=(s, s), pad=(p, p), num_filter=co,
                                   no_bias=True)._data
                # carry depends on EVERY output element (a single-element
                # carry lets XLA slice the conv down to one output pixel —
                # first cut "measured" 17,000 TFLOP/s that way)
                return jnp.sum(y.astype(jnp.float32)) * 1e-30

            return (jnp.float32(0.0), body,
                    2 * B * ho * ho * co * ci * k * k, None)
        return build

    for name, ci, co, hw, k, s, p in [
        ("conv7x7s2_3to64_224", 3, 64, 224, 7, 2, 3),
        ("conv3x3_64c_56", 64, 64, 56, 3, 1, 1),
        ("conv3x3_128c_28", 128, 128, 28, 3, 1, 1),
        ("conv3x3_256c_14", 256, 256, 14, 3, 1, 1),
        ("conv3x3_512c_7", 512, 512, 7, 3, 1, 1),
        ("conv1x1_64to256_56", 64, 256, 56, 1, 1, 0),
        ("conv1x1_256to64_56", 256, 64, 56, 1, 1, 0),
    ]:
        cases.append((f"rn50_{name}_bf16", make_conv(ci, co, hw, k, s, p)))

    # ---- bandwidth-bound tails of the ResNet step
    def build_bnrelu():
        x0 = arr((B, 256, 56, 56), "bfloat16")
        g, b, mm = arr((256,), "float32"), arr((256,), "float32"), \
            arr((256,), "float32")
        mv = jnp.abs(arr((256,), "float32")) + 1.0

        def body(i, c):
            y = nd.BatchNorm(_nd(c), _nd(g), _nd(b), _nd(mm), _nd(mv))._data
            return nd.relu(_nd(y))._data

        return x0, body, None, x0.size * 2 * 2  # read + write, bf16

    cases.append(("bn_relu_128x256x56x56_bf16", build_bnrelu))

    def build_add():
        x0 = arr((B, 256, 56, 56), "bfloat16")

        def body(i, c):
            return (c + x0) * jnp.bfloat16(0.5)

        return x0, body, None, x0.size * 3 * 2  # 2 reads + 1 write

    cases.append(("residual_add_128x256x56x56_bf16", build_add))

    def build_stream():
        big = arr((1 << 26,), "float32")  # 256 MB

        def body(i, c):
            return c + jnp.float32(1.0)

        return big, body, None, big.size * 4 * 2

    cases.append(("stream_add_256MB_f32", build_stream))

    # ---- FC / BERT shapes
    def build_fc():
        wfc = arr((1000, 2048), "bfloat16")

        def body(i, c):
            y = nd.FullyConnected(_nd(c), _nd(wfc), num_hidden=1000,
                                  no_bias=True)._data
            # 128x1000 -> feed back as 128x2048 via renormalized tile
            y = _renorm(y)
            return jnp.concatenate([y, y, y], axis=1)[:, :2048] \
                .astype(c.dtype)

        return (arr((128, 2048), "bfloat16"), body,
                2 * 128 * 2048 * 1000, None)

    cases.append(("fc_128x2048to1000_bf16", build_fc))

    def build_ffn():
        wf1 = arr((768, 3072), "bfloat16")
        wf2 = arr((3072, 768), "bfloat16")
        xb = arr((16384, 768), "bfloat16")

        def body(i, c):
            h = nd.dot(_nd(c), _nd(wf1))._data
            h = jnp.maximum(h, 0)
            return _renorm(nd.dot(_nd(h), _nd(wf2))._data).astype(c.dtype)

        return xb, body, 2 * 16384 * 768 * 3072 * 2, None

    cases.append(("bert_ffn_16384_768_3072_bf16", build_ffn))

    # ---- BERT-base bs=32 seq=512 attention internals (VERDICT r4 #2:
    # measure the asserted "attention tail" instead of guessing).
    # Shapes: (B, H, S, D) = (32, 12, 512, 64); tokens = B*S = 16384.
    BH, S, D = 32 * 12, 512, 64
    attn_flops = 2 * 2 * BH * S * S * D  # QK^T + PV, 2-FLOP convention

    def build_flash_fwd(use_pallas):
        def build():
            from mxnet_tpu.ops import attention as ATT
            q = arr((32, 12, S, D), "bfloat16")
            k = arr((32, 12, S, D), "bfloat16")
            v = arr((32, 12, S, D), "bfloat16")

            def body(i, c):
                o = ATT.flash_attention(c, k, v, use_pallas=use_pallas)
                return _renorm(o).astype(c.dtype)

            return q, body, attn_flops, None
        return build

    cases.append(("bert_flash_attn_fwd_pallas_bf16", build_flash_fwd(True)))
    cases.append(("bert_flash_attn_fwd_xlascan_bf16",
                  build_flash_fwd(False)))

    def build_flash_fwdbwd(use_pallas):
        def build():
            from mxnet_tpu.ops import attention as ATT
            q = arr((32, 12, S, D), "bfloat16")
            k = arr((32, 12, S, D), "bfloat16")
            v = arr((32, 12, S, D), "bfloat16")

            def loss(q_, k_, v_):
                o = ATT.flash_attention(q_, k_, v_, use_pallas=use_pallas)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            gfn = jax.grad(loss, argnums=(0, 1, 2))

            def body(i, c):
                dq, dk, dv = gfn(c, k, v)
                return _renorm(dq).astype(c.dtype)

            # fwd (2 matmuls) + bwd (5 matmuls: dq, dk, dv, 2 recompute)
            return q, body, attn_flops * 7 // 2, None
        return build

    cases.append(("bert_flash_attn_fwdbwd_pallas_bf16",
                  build_flash_fwdbwd(True)))
    cases.append(("bert_flash_attn_fwdbwd_xlascan_bf16",
                  build_flash_fwdbwd(False)))

    def build_softmax():
        x0 = arr((BH, S, S), "bfloat16")

        def body(i, c):
            y = nd.softmax(_nd(c), axis=-1)._data
            return (y * jnp.bfloat16(2.0) - jnp.bfloat16(0.5)).astype(
                c.dtype)

        # unfused S^2 softmax: what the flash kernel avoids materializing
        return x0, body, None, x0.size * 2 * 2

    cases.append(("bert_softmax_384x512x512_bf16", build_softmax))

    def build_layernorm():
        x0 = arr((16384, 768), "bfloat16")
        g = arr((768,), "float32")
        b2 = arr((768,), "float32")

        def body(i, c):
            y = nd.LayerNorm(_nd(c), _nd(g), _nd(b2))._data
            return (y + jnp.bfloat16(0.01)).astype(c.dtype)

        return x0, body, None, x0.size * 2 * 2

    cases.append(("bert_layernorm_16384x768_bf16", build_layernorm))

    def build_bias_gelu():
        x0 = arr((16384, 3072), "bfloat16")
        b3 = arr((3072,), "float32")

        def body(i, c):
            y = nd.Activation(_nd(c + b3.astype(c.dtype)),
                              act_type="gelu")._data
            return _renorm(y).astype(c.dtype)

        return x0, body, None, x0.size * 2 * 2

    cases.append(("bert_bias_gelu_16384x3072_bf16", build_bias_gelu))

    def build_dropout():
        x0 = arr((16384, 768), "bfloat16")
        key = jax.random.PRNGKey(7)

        def body(i, c):
            k = jax.random.fold_in(key, i)
            keep = jax.random.bernoulli(k, 0.9, c.shape)
            return jnp.where(keep, c / jnp.bfloat16(0.9),
                             jnp.bfloat16(0.0))

        return x0, body, None, x0.size * 2 * 2

    cases.append(("bert_dropout_16384x768_bf16", build_dropout))

    def build_qkv_proj():
        w = arr((768, 768), "bfloat16")

        def body(i, c):
            return _renorm(nd.dot(_nd(c), _nd(w))._data).astype(c.dtype)

        return (arr((16384, 768), "bfloat16"), body,
                2 * 16384 * 768 * 768, None)

    cases.append(("bert_proj_16384x768x768_bf16", build_qkv_proj))

    return cases


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n1", type=int, default=20)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--target-delta", type=float, default=2.0,
                    help="seconds of on-chip work between the two "
                    "timed iteration counts")
    ap.add_argument("--ops", type=str, default="",
                    help="comma-separated substring filter")
    args = ap.parse_args()

    backend = jax.default_backend()
    wanted = [s for s in args.ops.split(",") if s]
    rng = onp.random.RandomState(0)

    results = []
    for name, build in _cases(rng):
        if wanted and not any(w in name for w in wanted):
            continue
        # per-case isolation: one transient tunnel error must not kill
        # the remaining sweep (a mid-sweep remote-compile reset cost the
        # first round-4 run its bandwidth rows)
        try:
            init, body, flops, nbytes = build()
            sec = _time_loop(body, init, args.n1, args.reps,
                             target_delta=args.target_delta)
        except Exception as e:  # pragma: no cover - platform-dependent
            print(json.dumps({"op": name, "error":
                              f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)
            continue
        rec = {"op": name, "usec": round(sec * 1e6, 2)}
        if flops:
            rec["tflops"] = round(flops / sec / 1e12, 2)
        if nbytes:
            rec["gbps"] = round(nbytes / sec / 1e9, 1)
        results.append(rec)
        print(json.dumps(rec), flush=True)
    print(json.dumps({"summary": True, "backend": backend,
                      "method": "chained-fori_loop slope",
                      "ops_measured": len(results)}))


if __name__ == "__main__":
    main()
