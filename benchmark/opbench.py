#!/usr/bin/env python
"""Per-operator micro-benchmark (reference benchmark/opperf/: runs every
registered op with synthetic shapes and reports per-op latency).

Sweeps a representative slice of the nd op surface — MXU ops (dot, FC,
conv), reductions, normalizations, elementwise, shape ops — at small and
large synthetic shapes. For each (op, shape): median wall microseconds
over ``--iters`` timed calls (after warmup, with a host-fetch flush, the
only reliable sync on tunneled TPU platforms) plus achieved GFLOP/s from
an analytic FLOP count where one is meaningful.

Prints one JSON line per measurement and a trailing summary line. A CPU
reference output is committed at benchmark/opbench.reference.json for
regression eyeballing (absolute numbers are machine-dependent; the
structure and op coverage are the contract).

Run: python benchmark/opbench.py [--iters 30] [--ops dot,conv,...]
"""
import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    # honor the env override even where a sitecustomize pre-imported jax
    # pinned to an accelerator platform (axon images)
    import jax
    jax.config.update("jax_platforms", "cpu")


def _cases():
    """(name, build() -> (fn, flops)) — fn is a nullary closure over
    prebuilt device arrays; flops=None for ops without a natural count."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rng = onp.random.RandomState(0)

    def arr(*shape):
        return nd.array(rng.randn(*shape).astype("float32"))

    cases = []

    def add(name, fn, flops=None):
        cases.append((name, fn, flops))

    for n in (256, 1024):
        a, b = arr(n, n), arr(n, n)
        add(f"dot_{n}x{n}", lambda a=a, b=b: nd.dot(a, b), 2 * n ** 3)
    x = arr(64, 512)
    w = arr(512, 512)
    bias = arr(512)
    add("fully_connected_64x512",
        lambda x=x, w=w, b=bias: nd.FullyConnected(x, w, b, num_hidden=512),
        2 * 64 * 512 * 512)
    for hw, c in ((32, 32), (64, 64)):
        xc = arr(8, c, hw, hw)
        wc = arr(c, c, 3, 3)
        flops = 2 * 8 * c * c * 9 * hw * hw
        add(f"conv3x3_{c}c_{hw}px",
            lambda xc=xc, wc=wc: nd.Convolution(
                xc, wc, kernel=(3, 3), pad=(1, 1), num_filter=wc.shape[0]),
            flops)
    xp = arr(8, 32, 64, 64)
    add("maxpool2x2", lambda xp=xp: nd.Pooling(xp, kernel=(2, 2),
                                               stride=(2, 2),
                                               pool_type="max"))
    g, beta = arr(64), arr(64)
    mm, mv = arr(64), nd.array(onp.abs(rng.randn(64)).astype("float32"))
    xb = arr(32, 64, 16, 16)
    add("batchnorm_infer",
        lambda xb=xb, g=g, b=beta, m=mm, v=mv: nd.BatchNorm(
            xb, g, b, m, v, use_global_stats=True),
        4 * xb.size)
    xl = arr(64, 512)
    add("layernorm", lambda xl=xl, g2=arr(512), b2=arr(512):
        nd.LayerNorm(xl, g2, b2), 8 * 64 * 512)
    for n in (1 << 16, 1 << 22):
        xe = arr(n)
        add(f"relu_{n}", lambda xe=xe: nd.relu(xe), n)
        add(f"exp_{n}", lambda xe=xe: nd.exp(xe), n)
    xa, xb2 = arr(1 << 20), arr(1 << 20)
    add("broadcast_add_1M", lambda a=xa, b=xb2: a + b, 1 << 20)
    xs = arr(128, 1000)
    add("softmax_128x1000", lambda xs=xs: nd.softmax(xs), 5 * 128 * 1000)
    xr = arr(1 << 20)
    add("sum_1M", lambda xr=xr: nd.sum(xr), 1 << 20)
    xt = arr(512, 512)
    add("transpose_512", lambda xt=xt: nd.transpose(xt))
    add("concat_2x1M", lambda a=xa, b=xb2: nd.concat(a, b, dim=0))
    xk = arr(1024, 128)
    add("topk_1024x128", lambda xk=xk: nd.topk(xk, k=8, axis=-1))
    xso = arr(4096, 64)
    add("sort_4096x64", lambda xso=xso: nd.sort(xso, axis=-1))
    add("embedding_64x128",
        lambda idx=nd.array(rng.randint(0, 1000, (64, 128))
                            .astype("int32")), w=arr(1000, 64):
        nd.Embedding(idx, w, input_dim=1000, output_dim=64))
    # second tier: deconv, batched matmul, activations, shape/index ops
    xd = arr(8, 32, 16, 16)
    wd = arr(32, 16, 2, 2)
    # kernel 2 stride 2: each INPUT pixel contributes k*k taps; counting
    # by inputs avoids over-counting the stride-partitioned output
    add("deconv2x2_stride2",
        lambda xd=xd, wd=wd: nd.Deconvolution(
            xd, wd, kernel=(2, 2), stride=(2, 2), num_filter=16),
        2 * 8 * 32 * 16 * 4 * 16 * 16)
    ba, bb = arr(64, 128, 64), arr(64, 64, 128)
    add("batch_dot_64x128x64",
        lambda a=ba, b=bb: nd.batch_dot(a, b), 2 * 64 * 128 * 64 * 128)
    xg = arr(64, 1024)
    for act in ("sigmoid", "tanh", "gelu"):
        add(f"{act}_64x1024",
            lambda xg=xg, act=act: getattr(nd, act)(xg), 64 * 1024)
    add("log_softmax_128x1000",
        lambda xs=xs: nd.log_softmax(xs), 5 * 128 * 1000)
    add("avgpool2x2", lambda xp=xp: nd.Pooling(
        xp, kernel=(2, 2), stride=(2, 2), pool_type="avg"))
    add("global_avg_pool", lambda xp=xp: nd.Pooling(
        xp, global_pool=True, pool_type="avg"))
    xt2 = arr(1 << 18)
    add("cumsum_256k", lambda x=xt2: nd.cumsum(x))
    cond = xa > 0  # prebuilt: the timed fn measures where alone
    add("where_1M", lambda c=cond, a=xa, b=xb2: nd.where(c, a, b),
        1 << 20)
    add("take_rows", lambda w=arr(4096, 256),
        idx=nd.array(rng.randint(0, 4096, 1024).astype("int32")):
        nd.take(w, idx))
    add("tile_2x", lambda x=arr(512, 128): nd.tile(x, reps=(2, 2)))
    add("pad_edge", lambda x=arr(8, 16, 32, 32): nd.pad(
        x, mode="edge", pad_width=(0, 0, 0, 0, 2, 2, 2, 2)))
    add("one_hot_32k", lambda idx=nd.array(
        rng.randint(0, 512, 32768).astype("int32")):
        nd.one_hot(idx, depth=512))
    T, N, C, H = 32, 16, 64, 128
    from mxnet_tpu.ops.rnn import rnn_packed_param_size
    npk = rnn_packed_param_size("lstm", C, H, 1, False)
    xr2 = arr(T, N, C)
    pv = arr(npk)
    add("lstm_T32_N16_H128",
        lambda x=xr2, p=pv: nd.RNN(x, p, state_size=H, mode="lstm"),
        2 * T * N * 4 * H * (C + H))
    return cases


def _flush(out):
    x = out[0] if isinstance(out, (list, tuple)) else out
    x.asnumpy()  # host fetch: the only reliable flush on tunneled TPU


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--ops", type=str, default="",
                    help="comma-separated substring filter")
    args = ap.parse_args()
    import jax
    backend = jax.default_backend()
    wanted = [s for s in args.ops.split(",") if s]

    results = []
    for name, fn, flops in _cases():
        if wanted and not any(w in name for w in wanted):
            continue
        for _ in range(args.warmup):
            _flush(fn())
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            _flush(fn())
            times.append(time.perf_counter() - t0)
        med = float(onp.median(times))
        rec = {"op": name, "usec": round(med * 1e6, 1),
               "gflops": round(flops / med / 1e9, 2) if flops else None}
        results.append(rec)
        print(json.dumps(rec), flush=True)
    print(json.dumps({"summary": True, "backend": backend,
                      "ops_measured": len(results),
                      "total_usec": round(sum(r["usec"]
                                              for r in results), 1)}))


if __name__ == "__main__":
    main()
