#!/usr/bin/env python
"""Attribute the executed-vs-analytic FLOP multiplier of the ResNet-50
train step (VERDICT r4 weak #1 / next-round #1).

Compiles four nested programs at the bench config (bf16 AMP, bs=128 by
default) and reads XLA's own cost model for each:

  fwd-eval    — inference forward (the analytic "1x")
  fwd-train   — training forward incl. BN batch stats
  fwd+bwd     — value_and_grad, no update
  full step   — fwd + bwd + SGD-momentum update (the bench program)

and then walks the optimized HLO of each, summing the algebraic FLOPs of
every convolution op from its logical shapes — so the delta between
"XLA-counted" and "HLO-conv-algebra" isolates non-conv FLOPs, and the
conv-op census (count × shape) between fwd+bwd and fwd exposes
rematerialized convolutions directly.

Usage: python benchmark/flops_attrib.py [bs] [--fp32]
Writes a JSON summary to benchmark/flops_attrib.json and dumps each
program's HLO to /tmp/flops_attrib_<name>.hlo.
"""
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import jax
import jax.numpy as jnp


def _parse_shape(s):
    m = re.match(r"(\w+)\[([\d,]*)\]", s)
    if not m:
        return None, ()
    dims = tuple(int(d) for d in m.group(2).split(",") if d)
    return m.group(1), dims


def conv_census(hlo_text):
    """[(result_shape, operand_shapes, window, flops)] for every
    convolution op, with algebraic FLOPs = 2 * prod(out) * (reduction
    size per output element) derived from dnums + window. Operand shapes
    are resolved through the HLO def-use text (optimized HLO names
    operands like %fusion.396 with the shape on the defining line)."""
    defs = {}
    for line in hlo_text.splitlines():
        dm = re.match(r"\s*(%[\w.\-]+)\s*=\s*((?:\([^)]*\))|(?:\w+\[[\d,]*\]))",
                      line)
        if dm:
            defs[dm.group(1)] = dm.group(2)
    out = []
    for line in hlo_text.splitlines():
        if "convolution(" not in line or "=" not in line:
            continue
        m = re.search(r"=\s+(\w+\[[\d,]*\])\S*\s+convolution\(([^)]*)\)",
                      line)
        if not m:
            continue
        res_s = m.group(1)
        _, res_dims = _parse_shape(res_s)
        ops = [o.strip() for o in m.group(2).split(",")]
        opshapes = []
        for o in ops:
            sm = re.search(r"(\w+\[[\d,]*\])", o)
            if sm:
                opshapes.append(sm.group(1))
            else:
                nm = re.match(r"(%[\w.\-]+)", o)
                opshapes.append(defs.get(nm.group(1), o) if nm else o)
        wm = re.search(r"window={size=([\dx]+)", line)
        win = tuple(int(x) for x in wm.group(1).split("x")) if wm else ()
        dm2 = re.search(r"dim_labels=(\S+?)[ ,]", line)
        dl = dm2.group(1) if dm2 else ""
        fgc = re.search(r"feature_group_count=(\d+)", line)
        fgc = int(fgc.group(1)) if fgc else 1
        bgc = re.search(r"batch_group_count=(\d+)", line)
        bgc = int(bgc.group(1)) if bgc else 1
        # reduction size = kernel-input-feature * prod(window)
        _, rhs_dims = _parse_shape(opshapes[1])
        kin = None
        if dl and rhs_dims:
            # dim_labels like b01f_01io->b01f or bf01_oi01->bf01
            rhs_labels = dl.split("_")[1].split("-")[0]
            idx = rhs_labels.index("i")
            if idx < len(rhs_dims):
                kin = rhs_dims[idx]
        red = (kin if kin is not None else 1)
        for w in win:
            red *= w
        flops = 2 * red
        for d in res_dims:
            flops *= d
        out.append({"result": res_s, "operands": opshapes,
                    "window": win, "labels": dl, "fgc": fgc, "bgc": bgc,
                    "gflops": flops / 1e9, "line_meta": line[-120:]})
    return out


def stablehlo_conv_algebra(lowered_text):
    """Sum algebraic conv FLOPs (2 * prod(out) * reduction-size) over all
    stablehlo.convolution ops, dimension-numbers-aware so forward,
    backward-input and backward-filter forms all count correctly."""
    pat = re.compile(
        r"stablehlo\.convolution\(.*?dim_numbers = "
        r"\[([^\]]*)\]x\[([^\]]*)\]->\[([^\]]*)\].*?"
        r":\s*\(tensor<([^>]+)>,\s*tensor<([^>]+)>\)\s*->\s*tensor<([^>]+)>",
        re.S)
    total = 0.0
    n = 0
    for m in pat.finditer(lowered_text):
        _, rhs_spec, _, _, rhs_s, out_s = m.groups()
        rhs_tokens = [t.strip() for t in rhs_spec.split(",")]
        rhs_dims = tuple(int(d) for d in rhs_s.split("x")[:-1])
        out_dims = tuple(int(d) for d in out_s.split("x")[:-1])
        red = rhs_dims[rhs_tokens.index("i")]
        for tok, d in zip(rhs_tokens, rhs_dims):
            if tok.isdigit():
                red *= d
        flops = 2.0 * red
        for d in out_dims:
            flops *= d
        total += flops
        n += 1
    return total / 1e9, n


def _flops(comp):
    ca = comp.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    return float(ca.get("flops", 0.0)), float(ca.get("bytes accessed", 0.0))


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    bs = int(args[0]) if args else 128
    use_amp = "--fp32" not in sys.argv

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu import _tape
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from mxnet_tpu.ndarray.ndarray import NDArray
    from __graft_entry__ import (make_train_step, _init_net,
                                 _functional_apply)

    onp.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    params = _init_net(net, (1, 3, 224, 224))
    if use_amp:
        mx.amp.init()
    try:
        pd = tuple(jnp.array(p._data._data, copy=True) for p in params)
        mom = tuple(jnp.zeros_like(d) for d in pd)
        x = jnp.asarray(onp.random.uniform(
            size=(bs, 3, 224, 224)).astype("float32"))
        y = jnp.asarray(onp.random.randint(
            0, 1000, size=(bs,)).astype("int32"))
        key = jax.random.PRNGKey(0)

        fwd_eval = _functional_apply(net, params, train=False)
        fwd_train = _functional_apply(net, params, train=True,
                                      with_state=True)
        loss_blk = SoftmaxCrossEntropyLoss()

        def eval_prog(pd, x, key):
            return fwd_eval(pd, x, key)

        def train_fwd_prog(pd, x, key):
            logits, state = fwd_train(pd, x, key)
            prev = _tape.set_recording(False)
            try:
                l = loss_blk.forward(NDArray(logits), NDArray(y))
            finally:
                _tape.set_recording(prev)
            return jnp.mean(l._data), state

        def grad_prog(pd, x, key):
            (loss, state), grads = jax.value_and_grad(
                lambda p: train_fwd_prog(p, x, key), has_aux=True)(pd)
            return loss, grads

        step = make_train_step(net, params, lr=0.1)

        progs = {
            "fwd_eval": (eval_prog, (pd, x, key), ()),
            "fwd_train_loss": (train_fwd_prog, (pd, x, key), ()),
            "fwd_bwd": (grad_prog, (pd, x, key), ()),
            "full_step": (step, (pd, mom, x, y, key), (0, 1)),
        }
        report = {"bs": bs, "amp": use_amp, "programs": {}}
        for name, (fn, a, donate) in progs.items():
            lowered = jax.jit(fn, donate_argnums=donate).lower(*a)
            alg_g, alg_n = stablehlo_conv_algebra(lowered.as_text())
            comp = lowered.compile()
            fl, byt = _flops(comp)
            txt = comp.as_text()
            with open(f"/tmp/flops_attrib_{name}.hlo", "w") as f:
                f.write(txt)
            census = conv_census(txt)
            fus = txt.count(" fusion(")
            report["programs"][name] = {
                "xla_gflops": fl / 1e9,
                "xla_gflops_per_img": fl / 1e9 / bs,
                "bytes_gb": byt / 1e9,
                "n_conv_ops_compiled": len(census),
                "n_conv_sites_lowered": alg_n,
                "conv_algebra_gflops": alg_g,
                "xla_vs_conv_algebra": fl / 1e9 / alg_g if alg_g else None,
                "n_fusions": fus,
            }
            print(f"{name:15s} xla={fl/1e9:9.1f} G ({fl/1e9/bs:6.2f}/img) "
                  f"convs={len(census):3d} conv_algebra={alg_g:9.1f} G "
                  f"(x{fl/1e9/alg_g:4.2f}) bytes={byt/1e9:.1f} GB",
                  flush=True)

        with open("benchmark/flops_attrib.json", "w") as f:
            json.dump(report, f, indent=1)
        print("wrote benchmark/flops_attrib.json")
    finally:
        if use_amp:
            mx.amp.uninit()


if __name__ == "__main__":
    main()
