#!/usr/bin/env python
"""Dist-kvstore transfer-path micro-benchmark (VERDICT r2 item 3 artifact).

Measures, on the 2-process local CPU rig, one ResNet-18-shaped gradient
set (62 dense arrays, ~11.7M params) pushed through KVStoreDist:

- per-key   : one device_put + collective + host sync PER PARAMETER
              (the reference's engine-op-per-key shape,
              src/kvstore/kvstore_dist.h without batching)
- fused     : KVStoreDist.pushpull_list — bucketed collectives
              (MXNET_KVSTORE_SLICE_THRESHOLD), all dispatched, ONE host
              sync per step

Run:  python benchmark/dist_kvbench.py          (self-launches 2 workers)
Prints one JSON line per mode with wall ms/step, collectives/step, and
host syncs (blocks)/step, plus the sync-reduction ratio.

Reference numbers (this rig, 2 CPU procs, 5 steps): see
benchmark/dist_kvbench.reference.json.
"""
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ResNet-18 parameter shapes (conv OIHW + BN vectors + fc), classes=1000
def resnet18_shapes():
    shapes = [(64, 3, 7, 7)]
    chans = [(64, 64), (64, 64), (64, 64), (64, 64),
             (128, 64), (128, 128), (128, 128), (128, 128),
             (256, 128), (256, 256), (256, 256), (256, 256),
             (512, 256), (512, 512), (512, 512), (512, 512)]
    for o, i in chans:
        shapes.append((o, i, 3, 3))
    for o, i in ((128, 64), (256, 128), (512, 256)):
        shapes.append((o, i, 1, 1))  # downsample convs
    for c in [64] + [o for o, _ in chans]:
        shapes.append((c,))  # gamma
        shapes.append((c,))  # beta
    shapes.append((1000, 512))
    shapes.append((1000,))
    return shapes


def worker(outdir):
    os.environ["XLA_FLAGS"] = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as onp
    sys.path.insert(0, REPO)
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.parallel import dist

    dist.initialize()
    rank = jax.process_index()
    shapes = resnet18_shapes()
    rng = onp.random.RandomState(rank)
    steps = 5
    report = {}

    for mode in ("perkey", "fused"):
        kv = mx.kvstore.create("dist_sync")
        grads = [nd.array(rng.randn(*s).astype("float32")) for s in shapes]
        # warmup (compile the collectives)
        if mode == "fused":
            kv.pushpull_list(list(range(len(grads))), grads)
        else:
            for i, g in enumerate(grads):
                kv.pushpull(i, g)
        kv.stats = {"collectives": 0, "blocks": 0}
        t0 = time.perf_counter()
        for _ in range(steps):
            if mode == "fused":
                kv.pushpull_list(list(range(len(grads))), grads)
            else:
                for i, g in enumerate(grads):
                    kv.pushpull(i, g)
        dt = time.perf_counter() - t0
        report[mode] = {
            "ms_per_step": round(dt / steps * 1e3, 2),
            "collectives_per_step": kv.stats["collectives"] / steps,
            "host_syncs_per_step": kv.stats["blocks"] / steps,
        }
    if rank == 0:
        report["nparams"] = len(shapes)
        report["sync_reduction"] = (
            report["perkey"]["host_syncs_per_step"]
            / max(report["fused"]["host_syncs_per_step"], 1))
        with open(os.path.join(outdir, "kvbench.json"), "w") as f:
            json.dump(report, f, indent=1)
        print(json.dumps(report))


def main():
    import tempfile
    outdir = tempfile.mkdtemp()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cmd = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
           "-n", "2", "--launcher", "local", "-p", str(port),
           sys.executable, os.path.abspath(__file__), "--worker", outdir]
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=900,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode("utf-8", "replace")
    if proc.returncode != 0:
        sys.exit(f"launch failed:\n{out[-3000:]}")
    path = os.path.join(outdir, "kvbench.json")
    print(open(path).read())


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker(sys.argv[sys.argv.index("--worker") + 1])
    else:
        main()
