#!/usr/bin/env python
"""Per-step host-overhead microbench: eager tape loop vs fused compile_step.

Non-gating. Quantifies what the fused whole-train-step buys on the HOST
side: the eager loop walks the Python tape (one vjp closure per op) and
crosses a host boundary between backward and the jitted optimizer update
every iteration; ``Trainer.compile_step`` dispatches ONE compiled
program per step plus a thin writeback. On a tiny MLP the device work is
negligible, so wall time ~= host overhead — the quantity that caps LSTM/
small-batch MFU (ISSUE 1, BENCH_r05: 0.17 LSTM MFU vs 148 TFLOP/s
roofline).

    JAX_PLATFORMS=cpu python benchmark/step_overhead.py

Prints one JSON line:
  {"metric": "train_step_host_overhead", "eager_ms": .., "fused_ms": ..,
   "speedup": .., "steps": N, "device": "..."}
"""
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, nd  # noqa: E402
from mxnet_tpu.gluon import Trainer, TrainLoop, nn  # noqa: E402
from mxnet_tpu.gluon import loss as gloss  # noqa: E402


def build_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, in_units=32, activation="relu"),
            nn.Dense(64, in_units=64, activation="relu"),
            nn.Dense(8, in_units=64))
    net.initialize()
    return net


def main():
    steps = int(os.environ.get("MXNET_STEP_OVERHEAD_STEPS", "200"))
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(16, 32).astype("float32"))
    y = nd.array(rng.randint(0, 8, size=(16,)).astype("int32"))
    loss_blk = gloss.SoftmaxCrossEntropyLoss()

    # ---- eager record/backward/step loop ----
    net_e = build_net()
    tr_e = Trainer(net_e.collect_params(), "sgd",
                   {"learning_rate": 0.05, "momentum": 0.9})
    for _ in range(10):  # warmup: compile per-op kernels + fused update
        with autograd.record():
            l = loss_blk(net_e(x), y)
        l.backward()
        tr_e.step(16)
    jax.block_until_ready(l._data)
    t0 = time.perf_counter()
    for _ in range(steps):
        with autograd.record():
            l = loss_blk(net_e(x), y)
        l.backward()
        tr_e.step(16)
    jax.block_until_ready(l._data)
    eager_ms = (time.perf_counter() - t0) / steps * 1e3

    # ---- fused whole-step program ----
    net_f = build_net()
    tr_f = Trainer(net_f.collect_params(), "sgd",
                   {"learning_rate": 0.05, "momentum": 0.9})
    loop = TrainLoop(net_f, tr_f, loss_blk)
    loop.compiled_step.aot_compile(x, y)
    for _ in range(10):
        l = loop.step(x, y)
    jax.block_until_ready(l._data)
    t0 = time.perf_counter()
    for _ in range(steps):
        l = loop.step(x, y)
    jax.block_until_ready(l._data)
    fused_ms = (time.perf_counter() - t0) / steps * 1e3

    assert loop.compiled_step.mode == "fused", loop.compiled_step.mode
    print(json.dumps({
        "metric": "train_step_host_overhead",
        "eager_ms": round(eager_ms, 3),
        "fused_ms": round(fused_ms, 3),
        "speedup": round(eager_ms / fused_ms, 2) if fused_ms else None,
        "steps": steps,
        "n_traces": loop.compiled_step.n_traces,
        "device": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
