#!/usr/bin/env python
"""Parse training output logs into a markdown (or TSV) table.

Reference analog: tools/parse_log.py — same CLI and the same
``Epoch[N] Train-<metric>=V`` / ``Validation-<metric>=V`` /
``Epoch[N] Time cost=V`` line grammar, extended to also match this
framework's estimator LoggingHandler lines
(``[Epoch N] train <metric>: V``, gluon/contrib/estimator).
"""
import argparse
import re
import sys


def parse_lines(lines, metric_names):
    """-> {epoch: [sum, count] * (2*len(metrics)+1)} accumulator rows:
    train metrics, then val metrics, then epoch time."""
    res = [re.compile(r".*Epoch\[(\d+)\] Train-" + s + r".*=([.\d]+)")
           for s in metric_names]
    res += [re.compile(r".*Epoch\[(\d+)\] Validation-" + s + r".*=([.\d]+)")
            for s in metric_names]
    res += [re.compile(r".*Epoch\[(\d+)\] Time.*=([.\d]+)")]
    # estimator LoggingHandler grammar
    est = [re.compile(r".*\[Epoch (\d+)\].*[Tt]rain " + s + r": ([.\d]+)")
           for s in metric_names]
    est += [re.compile(r".*\[Epoch (\d+)\].*[Vv]al(?:idation)? " + s +
                       r": ([.\d]+)") for s in metric_names]
    est += [re.compile(r".*\[Epoch (\d+)\].*time.*?: ([.\d]+)")]

    n_slots = 2 * len(metric_names) + 1
    data = {}
    for line in lines:
        for table in (res, est):
            for i, r in enumerate(table):
                m = r.match(line)
                if m is not None:
                    epoch = int(m.group(1))
                    val = float(m.group(2))
                    row = data.setdefault(epoch, [0.0, 0] * n_slots)
                    row[i * 2] += val
                    row[i * 2 + 1] += 1
                    break
            else:
                continue
            break
    return data


def format_table(data, metric_names, fmt):
    heads = (["train-" + s for s in metric_names] +
             ["val-" + s for s in metric_names] + ["time"])
    rows = []
    for epoch in sorted(data):
        v = data[epoch]
        cells = []
        for j in range(len(heads)):
            cnt = v[2 * j + 1]
            cells.append("%f" % (v[2 * j] / cnt) if cnt else "-")
        rows.append((epoch + 1, cells))
    out = []
    if fmt == "markdown":
        out.append("| epoch | " + " | ".join(heads) + " |")
        out.append("| --- " * (len(heads) + 1) + "|")
        for epoch, cells in rows:
            out.append("| %2d | " % epoch + " | ".join(cells) + " |")
    else:
        out.append("\t".join(["epoch"] + heads))
        for epoch, cells in rows:
            out.append("\t".join(["%2d" % epoch] + cells))
    return "\n".join(out)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Parse training output log")
    parser.add_argument("logfile", nargs=1, type=str,
                        help="the log file for parsing")
    parser.add_argument("--format", type=str, default="markdown",
                        choices=["markdown", "none"],
                        help="the format of the parsed output")
    parser.add_argument("--metric-names", type=str, nargs="+",
                        default=["accuracy"],
                        help="names of metrics in log to parse")
    args = parser.parse_args(argv)
    with open(args.logfile[0]) as f:
        lines = f.readlines()
    data = parse_lines(lines, args.metric_names)
    print(format_table(data, args.metric_names, args.format))
    return 0


if __name__ == "__main__":
    sys.exit(main())
