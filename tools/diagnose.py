#!/usr/bin/env python
"""Diagnose the runtime environment for bug reports.

Reference analog: tools/diagnose.py — same sections (platform, python,
environment variables, build info) with the network-connectivity checks
made opt-in (``--network``): this framework targets egress-less
environments, and the useful diagnostics here are the accelerator ones
(jax backend, device kind, donation/compile sanity).
"""
import argparse
import os
import platform
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def check_pip():
    print("------------Pip Info-----------")
    try:
        import pip
        print("Version      :", pip.__version__)
    except ImportError:
        print("No corresponding pip install for current python.")


def check_mxnet():
    print("----------MXNet(TPU) Info-----------")
    try:
        import mxnet_tpu as mx
        print("Version      :", getattr(mx, "__version__", "dev"))
        print("Directory    :", os.path.dirname(mx.__file__))
        from mxnet_tpu.runtime import Features
        feats = Features()
        on = [f for f in feats.keys() if feats.is_enabled(f)]
        print("Enabled features:", ", ".join(sorted(on)))
    except Exception as e:  # pragma: no cover - env-dependent
        print("mxnet_tpu import failed:", repr(e))


def check_accelerator():
    print("----------Accelerator Info----------")
    try:
        import jax
        print("jax version  :", jax.__version__)
        print("backend      :", jax.default_backend())
        for d in jax.devices():
            print("device       :", d,
                  getattr(d, "device_kind", ""))
        import jax.numpy as jnp
        y = float((jnp.ones((8, 8)) @ jnp.ones((8, 8)))[0, 0])
        print("compile+run  : ok (8x8 matmul =", y, ")")
    except Exception as e:  # pragma: no cover - env-dependent
        print("accelerator check failed:", repr(e))


def check_analysis():
    """Compiled-program health: fuse a tiny MLP train step through
    Trainer.compile_step and print the mx.analysis ProgramReport
    (collective census, donation audit, host transfers, dtype drift) —
    so an environment report shows not just that the device compiles,
    but that the framework's ONE-program training contract holds on it
    (docs/ANALYSIS.md)."""
    print("----------Program Analysis----------")
    try:
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu.gluon import Trainer, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        onp.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        x = mx.nd.array(onp.random.randn(8, 16).astype("float32"))
        y = mx.nd.array(onp.random.randint(0, 8, size=(8,))
                        .astype("int32"))
        net(x)
        loss = SoftmaxCrossEntropyLoss()
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore=None)
        step = trainer.compile_step(lambda a, b: loss(net(a), b))
        step(x, y)
        report = step.analyze(x, y)
        print(report.summary())
        print("verdict      :", "OK" if report.ok else
              "VIOLATIONS (see findings above)")
    except Exception as e:  # pragma: no cover - env-dependent
        print("program analysis failed:", repr(e))


def check_engine():
    """Async-dispatch health: run a tiny MLP through the pipelined
    gluon.TrainLoop (device-prefetched inputs + bounded in-flight
    window) and print the dispatch stats — window size, host syncs per
    100 steps, prefetch depth/starvation — so a misconfigured pipeline
    (window 0, per-step syncs sneaking in, starved prefetcher) is
    visible without a profiler (docs/PERF_NOTES.md "async engine")."""
    print("----------Async Engine----------")
    try:
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu.analysis import guard as tguard
        from mxnet_tpu.gluon import Trainer, TrainLoop, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
        from mxnet_tpu.runtime import compile_cache_stats

        steps = 100
        onp.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        x = mx.nd.array(onp.random.randn(16, 16).astype("float32"))
        y = mx.nd.array(onp.random.randint(0, 8, size=(16,))
                        .astype("int32"))
        net(x)
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore=None)
        loop = TrainLoop(net, trainer, SoftmaxCrossEntropyLoss())
        loop.step(x, y)          # compile outside the counted region
        loop.synchronize()
        tguard.reset_sync_counts()
        for bx, by in loop.prefetch((x, y) for _ in range(steps)):
            loop.step(bx, by)
        loop.synchronize()
        counts = tguard.sync_counts()
        s = loop.engine_stats()
        print("mode         :", loop.compiled_step.mode)
        print("window size  :", s["inflight_window"],
              "(MXNET_INFLIGHT_STEPS)")
        print("steps run    :", steps)
        print("max in-flight:", s["max_pending"])
        print("window waits :", counts.get("window_retire", 0),
              "(the designed retire syncs)")
        print("host syncs   :", counts.get("wait_to_read", 0),
              f"per {steps} steps (unplanned NDArray syncs; want 0)")
        print("prefetch     : depth", s.get("prefetch_depth"),
              "starvation", s.get("starvation_count"),
              f"input_wait {s.get('input_wait_ms', 0.0):.1f} ms")
        cc = compile_cache_stats()
        if cc["enabled"]:
            print("compile cache:", cc["dir"],
                  f"hits={cc['hits']} misses={cc['misses']}")
        else:
            print("compile cache: off (set MXNET_COMPILE_CACHE=<dir>)")
    except Exception as e:  # pragma: no cover - env-dependent
        print("engine check failed:", repr(e))


def check_elastic():
    """Elastic-training health: run a tiny supervised TrainLoop, inject
    ONE fault mid-run (a device revocation when the world has >= 2
    devices, a transient IO error otherwise), and print the RecoveryLog
    table plus the restore provenance — the end-to-end proof that
    detection, mesh re-formation, and checkpoint recovery compose on
    this machine (docs/ROBUSTNESS.md "Elastic training")."""
    print("----------Elastic Supervisor----------")
    import tempfile
    try:
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu import elastic
        from mxnet_tpu.gluon import Trainer, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
        from mxnet_tpu.parallel import dist
        from mxnet_tpu.testing import faults

        ndev = len(dist.available_devices())
        print("world        :", ndev, "device(s) available")
        print("gates        : MXNET_ELASTIC",
              "on" if elastic.elastic_enabled() else "OFF",
              f"| max_retries {elastic.max_retries()}",
              f"| grace {elastic.preemption_grace_sec():.0f}s")

        def build():
            mx.random.seed(0)
            net = nn.HybridSequential()
            net.add(nn.Dense(16, in_units=8, activation="relu"),
                    nn.Dense(4, in_units=16))
            net.initialize()
            trainer = Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.1, "momentum": 0.9},
                              kvstore=None)
            return net, trainer, SoftmaxCrossEntropyLoss()

        def batch_fn(i):
            rng = onp.random.RandomState(100 + i)
            return (mx.nd.array(rng.randn(8, 8).astype("float32")),
                    mx.nd.array(rng.randint(0, 4, size=(8,))
                                .astype("int32")))

        if ndev >= 2:
            spec, mesh_axes = "step.dispatch:before=5:revoke:1", \
                {"dp": -1}
            print("injecting    : device revocation before step 5")
        else:
            spec, mesh_axes = "step.dispatch:before=5:error", None
            print("injecting    : transient IO error before step 5 "
                  "(single device: revocation cannot shrink)")
        log = elastic.RecoveryLog()
        with tempfile.TemporaryDirectory() as d:
            faults.configure(spec)
            try:
                sup = elastic.ElasticSupervisor(
                    build, d, mesh_axes=mesh_axes, checkpoint_every=2,
                    backoff_base=0.0, log=log)
                res = sup.run(batch_fn, 8)
            finally:
                faults.reset()
            print(f"run          : {res.final_step} steps, "
                  f"world {res.world_size}, "
                  f"{res.recoveries} recovery(ies), "
                  f"retries {res.retries}")
            mgr = sup.loop.checkpoint_manager if sup.loop else None
            prov = mgr.restore_provenance if mgr else None
            if prov:
                print(f"provenance   : restored step {prov['step']} "
                      f"from {os.path.basename(prov['resumed_from'])}"
                      + (f" ({prov['reshard']})" if prov.get("reshard")
                         else ""))
        print("-- recovery log --")
        print(log.table())
    except Exception as e:  # pragma: no cover - env-dependent
        print("elastic check failed:", repr(e))


def check_telemetry():
    """Runtime-telemetry health: run a tiny pipelined MLP TrainLoop with
    telemetry forced on and print (a) a metrics-registry snapshot of the
    headline series, (b) a 10-step timeline summary — p50/p99 duration
    per step phase — and (c) the live MFU estimate: cost_analysis FLOPs
    of the compiled step over measured step time, against a quickly
    measured matmul roofline (docs/OBSERVABILITY.md)."""
    print("----------Runtime Telemetry----------")
    try:
        import time
        import numpy as onp
        import jax
        import jax.numpy as jnp
        import mxnet_tpu as mx
        from mxnet_tpu import telemetry
        from mxnet_tpu.gluon import Trainer, TrainLoop, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        steps = 10
        onp.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        x = mx.nd.array(onp.random.randn(16, 16).astype("float32"))
        y = mx.nd.array(onp.random.randint(0, 8, size=(16,))
                        .astype("int32"))
        net(x)
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore=None)
        loop = TrainLoop(net, trainer, SoftmaxCrossEntropyLoss())
        telemetry.enable(True)
        loop.step(x, y)          # compile outside the measured region
        loop.synchronize()
        # quick measured roofline: achieved f32 matmul FLOP/s here
        m = 512
        a = jnp.asarray(onp.random.randn(m, m).astype("float32"))
        f = jax.jit(lambda a: a @ a)
        float(f(a).sum())
        t0 = time.perf_counter()
        for _ in range(5):
            c = f(a)
        float(c.sum())
        roofline = 5 * 2 * m ** 3 / (time.perf_counter() - t0)
        flops = loop.arm_mfu(x, y, peak_flops=roofline)
        telemetry.reset()
        loop.arm_mfu(x, y, peak_flops=roofline)   # re-arm post-reset
        for bx, by in loop.prefetch((x, y) for _ in range(steps)):
            loop.step(bx, by)
        loop.synchronize()

        names = telemetry.names
        print("-- registry snapshot (headline series) --")
        for name in (names.TRAIN_STEPS, names.WINDOW_RETIRES,
                     names.WINDOW_OCCUPANCY, names.PREFETCH_BATCHES,
                     names.PREFETCH_STARVATION, names.COMPILE_RETRACES,
                     names.CHECKPOINT_SAVES):
            print(f"{name:<36s}: {telemetry.value(name)}")
        hs = telemetry.registry().get(names.HOST_SYNCS).values()
        print(f"{names.HOST_SYNCS:<36s}: {hs or 0}")
        print(f"-- timeline summary (last {steps} steps) --")
        summary = telemetry.timeline().summary(last_steps=steps)
        print(f"{'phase':<12s}{'count':>6s}{'p50 ms':>10s}"
              f"{'p99 ms':>10s}{'max ms':>10s}")
        for phase, s in summary.items():
            print(f"{phase:<12s}{s['count']:>6d}{s['p50_ms']:>10.3f}"
                  f"{s['p99_ms']:>10.3f}{s['max_ms']:>10.3f}")
        print("-- MFU estimate --")
        print("step flops   :", flops, "(XLA cost_analysis)")
        print(f"roofline     : {roofline/1e9:.1f} GFLOP/s (measured "
              f"{m}^3 matmul)")
        fps = telemetry.value(names.MODEL_FLOPS_PER_SEC)
        mfu = telemetry.value(names.MFU)
        print("flops/sec    :",
              f"{fps/1e9:.3f} GFLOP/s" if fps else "n/a")
        print("mfu          :", f"{mfu:.6f}" if mfu else "n/a",
              "(tiny MLP: expect ~0; the gauge matters on real models)")
        wd = telemetry.watchdog()
        print("anomalies    :", len(wd.anomalies()) or "none")
        telemetry.enable(None)
    except Exception as e:  # pragma: no cover - env-dependent
        print("telemetry check failed:", repr(e))


def check_memory():
    """Device-memory health: compile a tiny MLP train step and print
    (a) the compiled program's memory report (argument/output/temp/
    generated-code/donated bytes + peak estimate), (b) the live-buffer
    census by pool with the jax.live_arrays() reconciliation (untracked
    bytes = suspected leaks), (c) per-device allocator stats with their
    source (allocator vs the documented live-array fallback on CPU),
    and (d) the MXNET_MEMORY_BUDGET headroom status
    (docs/OBSERVABILITY.md "memory")."""
    print("----------Device Memory----------")
    try:
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu import telemetry
        from mxnet_tpu.gluon import Trainer, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        onp.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        x = mx.nd.array(onp.random.randn(16, 16).astype("float32"))
        y = mx.nd.array(onp.random.randint(0, 8, size=(16,))
                        .astype("int32"))
        net(x)
        trainer = Trainer(net.collect_params(), "adam",
                          {"learning_rate": 1e-3}, kvstore=None)
        step = trainer.compile_step(
            lambda a, b: SoftmaxCrossEntropyLoss()(net(a), b))
        step(x, y)
        report = step.memory_report(x, y)
        print("-- compiled step (per shape bucket) --")
        if report is None:
            print("no compiled program (eager mode)")
        else:
            for k, v in report.to_dict().items():
                print(f"{k:<22s}: {v}")
        census = telemetry.memory.census()
        rec = census.reconcile()
        print("-- live-buffer census --")
        print(f"{'pool':<12s}{'buffers':>8s}{'bytes':>14s}")
        for pool in telemetry.memory.POOLS:
            print(f"{pool:<12s}{rec['counts'][pool]:>8d}"
                  f"{rec['by_pool'][pool]:>14d}")
        u = rec["untracked"]
        print(f"{'untracked':<12s}{u['count']:>8d}{u['bytes']:>14d}"
              "   (suspected leaks / user temporaries)")
        print("-- per-device stats --")
        for dev, s in telemetry.memory.device_memory_stats().items():
            print(f"{dev}: in_use={s['bytes_in_use']} "
                  f"peak={s['peak_bytes_in_use']} "
                  f"limit={s['bytes_limit']} (source={s['source']})")
        print("-- budget --")
        status = telemetry.memory.maybe_check_budget()
        if status is None:
            print("MXNET_MEMORY_BUDGET unset (no headroom check)")
        else:
            print(f"budget={status['budget']} in_use={status['in_use']} "
                  f"over={status['over']} (source={status['source']})")
        dd = telemetry.memory.dump_dir()
        print("OOM dumps    :", dd or
              "disabled (set MXNET_MEMORY_DUMP_DIR)")
    except Exception as e:  # pragma: no cover - env-dependent
        print("memory check failed:", repr(e))


def check_numerics():
    """Training-numerics health: compile a tiny MLP train step with
    per-layer numerics instrumentation and print a 10-step norm table
    (global grad/param norm, update/weight ratio, non-finite counts),
    then a simulated-divergence demo — one overflow batch producing
    exactly one nonfinite_grad anomaly with NaN-origin forensics naming
    the offending op and an atomic post-mortem dump
    (docs/OBSERVABILITY.md "numerics")."""
    print("----------Training Numerics----------")
    try:
        import tempfile
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu import nd, telemetry
        from mxnet_tpu.gluon import Trainer, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        steps = 10
        onp.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        x = mx.nd.array(onp.random.randn(16, 16).astype("float32"))
        y = mx.nd.array(onp.random.randint(0, 8, size=(16,))
                        .astype("int32"))
        net(x)
        loss = SoftmaxCrossEntropyLoss()
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore=None)
        step = trainer.compile_step(
            lambda a, b: loss(net(nd.exp(a * 0.1)), b),
            numerics="per_layer")
        print(f"-- {steps}-step norm table (MXNET_NUMERICS=per_layer) --")
        print(f"{'step':>4s}{'grad_norm':>12s}{'param_norm':>12s}"
              f"{'upd/w ratio':>13s}{'nonfinite':>10s}")
        for i in range(1, steps + 1):
            step(x, y)
            v = step.numerics_values()
            print(f"{i:>4d}{v['grad_norm']:>12.5f}"
                  f"{v['param_norm']:>12.5f}"
                  f"{v['update_ratio']:>13.6f}"
                  f"{v['nonfinite_total']:>10d}")
        top = sorted(v["layer_grad_norm"].items(),
                     key=lambda kv: -kv[1])[:3]
        print("largest layer grad norms:",
              ", ".join(f"{k}={n:.5f}" for k, n in top))

        print("-- simulated divergence (overflow batch) --")
        dump_dir = os.environ.get("MXNET_NUMERICS_DUMP_DIR") \
            or tempfile.mkdtemp(prefix="mx_numerics_")
        os.environ.setdefault("MXNET_NUMERICS_DUMP_DIR", dump_dir)
        xbad = mx.nd.array(onp.full((16, 16), 1200.0, "float32"))
        step(xbad, y)                  # exp overflows -> inf gradients
        v = step.numerics_values()
        print("nonfinite elements:", v["nonfinite_total"])
        events = telemetry.watchdog().anomalies("nonfinite_grad")
        print("anomalies    :", len(events), "(want exactly 1)")
        if events:
            print("message      :", events[0]["message"][:200])
        n_dumps = telemetry.value(telemetry.names.NUMERICS_DUMPS)
        print("dump files   :", int(n_dumps or 0), "in", dump_dir)
    except Exception as e:  # pragma: no cover - env-dependent
        print("numerics check failed:", repr(e))


def _fusion_leg(title, step, x, y):
    """Compile one train-step leg and print its fusion census: the
    kernel table (kind, ops, FLOPs, boundary bytes, bound class), the
    headline posture, and the top stranded ops."""
    step(x, y)
    report = step.analyze(x, y)
    fr = report.fusion
    print(f"-- {title} (mode={report.mode}) --")
    if fr is None:
        print("no compiled program (eager mode) — nothing to audit")
        return
    print(fr.summary_line())
    print(fr.table(top=12))
    if fr.stranded:
        print("top stranded ops (unfused between two fusions):")
        for s in fr.stranded[:5]:
            print(f"  {s.name:<36s} {s.opcode:<12s} {s.bytes:>10d} B "
                  f"between {s.producer} -> {','.join(s.consumers[:2])}")
    else:
        print("stranded ops : none above the "
              f"{fr.stranded_floor} B floor")
    if fr.boundaries:
        print("largest boundary materializations:")
        for b in fr.boundaries[:5]:
            print(f"  {b.name:<36s} {b.opcode:<12s} {b.bytes:>10d} B -> "
                  f"{len(b.consumers)} consumer(s)")


def check_fusion():
    """Fusion-census health (docs/ANALYSIS.md "Fusion census"): audit
    XLA's fusion decisions for two canonical legs — a tiny MLP and the
    LSTM-LM architecture of examples/train_lstm_lm.py (the worst-MFU
    BENCH leg) — printing each kernel's kind/ops/FLOPs/boundary bytes
    and bound class, plus any stranded ops the ideal-fusion diff of
    arXiv:2301.13062 flags."""
    print("----------Fusion Census----------")
    try:
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu.gluon import Trainer, nn, rnn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        onp.random.seed(0)
        loss = SoftmaxCrossEntropyLoss()

        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        x = mx.nd.array(onp.random.randn(16, 16).astype("float32"))
        y = mx.nd.array(onp.random.randint(0, 8, size=(16,))
                        .astype("int32"))
        net(x)
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore=None)
        step = trainer.compile_step(lambda a, b: loss(net(a), b))
        _fusion_leg("tiny MLP", step, x, y)

        class _LM(mx.gluon.HybridBlock):   # examples/train_lstm_lm.py
            def __init__(self, vocab, embed, hidden):
                super().__init__()
                self.emb = nn.Embedding(vocab, embed)
                self.lstm = rnn.LSTM(hidden, num_layers=1, layout="NTC")
                self.head = nn.Dense(vocab, flatten=False)

            def forward(self, tokens):
                return self.head(self.lstm(self.emb(tokens)))

        vocab = 16
        lm = _LM(vocab, 8, 16)
        lm.initialize()
        xt = mx.nd.array(onp.random.randint(0, vocab, size=(4, 8))
                         .astype("int32"))
        yt = mx.nd.array(onp.random.randint(0, vocab, size=(4, 8))
                         .astype("int32"))
        lm(xt)
        lm_tr = Trainer(lm.collect_params(), "adam",
                        {"learning_rate": 5e-3}, kvstore=None)
        lm_step = lm_tr.compile_step(lambda a, b: loss(lm(a), b))
        _fusion_leg("LSTM LM (worst-MFU leg)", lm_step, xt, yt)
    except Exception as e:  # pragma: no cover - env-dependent
        print("fusion check failed:", repr(e))


def check_sharding():
    """SPMD sharding-analysis health (docs/ANALYSIS.md "Sharding
    analysis"): compile the zero-sharded MLP on the virtual dp mesh
    and print its sharding-flow table (what layout every entry buffer
    actually got), the top implicit reshards, and the per-mesh-axis
    communication cost estimate."""
    print("----------Sharding Analysis----------")
    try:
        import numpy as onp
        import jax
        import mxnet_tpu as mx
        from mxnet_tpu.gluon import Trainer, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
        from mxnet_tpu.parallel import make_mesh, shard_batch
        from mxnet_tpu.analysis import sharding as asharding

        ndev = min(4, len(jax.devices()))
        if ndev < 2:
            print(f"only {ndev} device(s) — sharding analysis needs a "
                  ">=2-device mesh (virtual CPU mesh: "
                  "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
            return
        onp.random.seed(0)
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, in_units=16, activation="relu"),
                nn.Dense(8, in_units=32))
        net.initialize()
        loss = SoftmaxCrossEntropyLoss()
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore=None)
        step = trainer.compile_step(lambda a, b: loss(net(a), b))
        x = mx.nd.array(onp.random.randn(8, 16).astype("float32"))
        y = mx.nd.array(onp.random.randint(0, 8, size=(8,))
                        .astype("int32"))
        with make_mesh({"dp": ndev}, jax.devices()[:ndev]) as mesh:
            xs, ys = shard_batch(x, mesh), shard_batch(y, mesh)
            step(xs, ys)
            report = step.analyze(xs, ys)
        audit = report.sharding
        if audit is None or audit.table is None:
            print("no sharding audit available (eager path?)")
            return
        prof = asharding.bandwidth_profile()
        print(f"mode={report.mode} dp={ndev} pack={audit.pack} "
              f"profile={prof.name} ({prof.default_gbps} GB/s)")
        print()
        print("sharding-flow table (entry buffers):")
        print(audit.table.table_str(top=16))
        print()
        if audit.reshards:
            print("top implicit reshards (not implied by the spec):")
            for r in audit.reshards[:5]:
                print(f"  {r.name:<28s} {r.kind:<18s} "
                      f"{r.payload_bytes:>9d} B payload "
                      f"{r.wire_bytes:>9d} B wire  ~{r.seconds:.2e} s  "
                      f"(from `{r.producer or '?'}`)")
        else:
            print("implicit reshards: none above the "
                  f"{audit.reshard_floor} B floor — every collective "
                  "is implied by the declared spec")
        print()
        print("per-axis communication cost (ring model):")
        if audit.cost is not None:
            print(audit.cost.table_str(top=8))
        print()
        print(f"table digest: {audit.table.digest()}  "
              f"(pins layout identity across captures)")
    except Exception as e:  # pragma: no cover - env-dependent
        print("sharding check failed:", repr(e))


def check_overlap():
    """Exposed-communication posture (docs/PERF_NOTES.md "Communication
    overlap"): compile the zero-sharded adam MLP on the virtual dp mesh
    twice — monolithic serial baseline (zero.bucket_bytes=0) vs
    bucketed (16 KiB) — and print each schedule's per-collective
    overlap windows. The bucketed program should show a positive
    overlap fraction (bucket k's all-gather hides behind bucket k+1's
    update) where the serial baseline measures ~0."""
    print("----------Communication Overlap----------")
    try:
        import numpy as onp
        import jax
        import mxnet_tpu as mx
        from mxnet_tpu.gluon import Trainer, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
        from mxnet_tpu.parallel import make_mesh, shard_batch
        from mxnet_tpu.analysis.overlap import overlap_census
        from mxnet_tpu.tuning import space as tspace

        ndev = min(8, len(jax.devices()))
        if ndev < 2:
            print(f"only {ndev} device(s) — overlap analysis needs a "
                  ">=2-device mesh (virtual CPU mesh: "
                  "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
            return

        def census_for(bucket_bytes):
            onp.random.seed(3)
            mx.random.seed(3)
            net = nn.HybridSequential()
            net.add(nn.Dense(64, in_units=32, activation="relu"),
                    nn.Dense(48, activation="relu"), nn.Dense(10))
            net.initialize()
            loss = SoftmaxCrossEntropyLoss()
            x = mx.nd.array(onp.random.randn(64, 32).astype("float32"))
            y = mx.nd.array(onp.random.randint(0, 10, size=(64,))
                            .astype("float32"))
            net(x)   # materialize deferred-init params off-mesh
            trainer = Trainer(net.collect_params(), "adam",
                              {"learning_rate": 0.01}, kvstore=None)
            step = trainer.compile_step(lambda a, b: loss(net(a), b))
            with tspace.trial({"zero.shard_min_size": 1,
                               "zero.bucket_bytes": bucket_bytes}):
                with make_mesh({"dp": ndev}, jax.devices()[:ndev]) as m:
                    xs, ys = shard_batch(x, m), shard_batch(y, m)
                    step(xs, ys)
                    info = step.lower_entry(xs, ys)
                    hlo = info["lowered"].compile().as_text()
                    return overlap_census(hlo, mesh=m)

        for label, bb in (("serial (bucket_bytes=0)", 0),
                          ("bucketed (bucket_bytes=16384)", 16384)):
            rep = census_for(bb)
            print(f"{label}: {rep.summary_line()}")
            print(rep.table_str(top=8))
            print()
    except Exception as e:  # pragma: no cover - env-dependent
        print("overlap check failed:", repr(e))


def check_kernels():
    """Pallas kernel-layer health (docs/PERF_NOTES.md "Pallas kernel
    layer"): the MXNET_PALLAS dispatch decision (path + reason) for
    every kernel the gate knows, then an interpret-vs-XLA parity probe
    on a tiny LSTM scan and LayerNorm — the kernel BODY runs (as plain
    XLA ops) and its outputs diff against the reference path."""
    print("----------Pallas Kernel Layer----------")
    try:
        import numpy as onp
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.ops import kernels as K
        from mxnet_tpu.ops.kernels import norm as knorm
        from mxnet_tpu.ops.kernels import rnn_scan as krnn
        from mxnet_tpu.ops.rnn import scan_reference

        print(f"MXNET_PALLAS={K.pallas_mode()}  "
              f"backend={jax.default_backend()}")
        print(f"{'kernel':<18s}{'path':<11s}reason")
        for name in K.KERNELS:
            path, reason = K.dispatch(name)
            print(f"{name:<18s}{path:<11s}{reason}")

        onp.random.seed(0)
        T, N, H = 6, 8, 128
        xw = jnp.asarray(onp.random.randn(T, N, 4 * H)
                         .astype("float32") * 0.4)
        h0 = jnp.asarray(onp.random.randn(N, H).astype("float32"))
        c0 = jnp.asarray(onp.random.randn(N, H).astype("float32"))
        w = jnp.asarray((onp.random.randn(4 * H, H) * 0.3)
                        .astype("float32"))
        b = jnp.asarray((onp.random.randn(4 * H) * 0.1)
                        .astype("float32"))
        ys_r, _, _ = scan_reference(xw, h0, c0, w, b, "lstm")
        ys_k = krnn._scan_lstm("lstm", True, xw, h0, c0, w, b)[0]
        d = float(jnp.abs(ys_r - ys_k).max())
        print(f"lstm scan  interpret-vs-xla max|delta| = {d:.3e}"
              f"  ({'bit-exact' if d == 0.0 else 'nonzero'})")

        x = jnp.asarray(onp.random.randn(16, 256).astype("float32"))
        g = jnp.asarray(onp.random.randn(256).astype("float32"))
        be = jnp.asarray(onp.random.randn(256).astype("float32"))

        def ln_ref(x, g, be):       # the ops/nn.py reference recipe
            from jax import lax
            mean = jnp.mean(x, axis=-1, keepdims=True)
            var = jnp.var(x, axis=-1, keepdims=True)
            return (x - mean) * lax.rsqrt(var + 1e-5) * g + be

        ref = jax.jit(ln_ref)(x, g, be)
        ker = jax.jit(lambda x, g, be: knorm.layer_norm(
            x, g, be, interpret=True))(x, g, be)
        d = float(jnp.abs(ref - ker).max())
        print(f"layernorm  interpret-vs-xla max|delta| = {d:.3e}"
              f"  ({'bit-exact' if d == 0.0 else 'nonzero'})")
    except Exception as e:  # pragma: no cover - env-dependent
        print("kernel check failed:", repr(e))


def check_autotune():
    """Self-tuning autopilot health (docs/PERF_NOTES.md "Autotuner"):
    the registered tunable table (name, default, grid, consumer seam),
    then a 3-trial analytical sweep over a tiny MLP train step — shown
    twice against a scratch config DB so the report demonstrates BOTH
    halves of the loop: the cache MISS that searches + persists, and
    the cache HIT that replays the winner with zero trials."""
    print("----------Self-Tuning Autopilot----------")
    import tempfile
    try:
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu import tuning
        from mxnet_tpu.gluon import Trainer, nn
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        tuning.space.ensure_registered()
        print(f"MXNET_AUTOTUNE={tuning.autotune_mode()}  "
              f"backend={tuning.measure.backend_mode()}  "
              f"budget={tuning.budget_trials()}  "
              f"cache={tuning.cache_path() or '<memory>'}")
        print(f"{'tunable':<26s}{'default':>10s}  grid / seam")
        for row in tuning.space.table():
            print(f"{row['name']:<26s}{str(row['default']):>10s}  "
                  f"{list(row['grid'])}")
            print(f"{'':<38s}-> {row['seam']}")

        def build_step():
            onp.random.seed(0)
            net = nn.HybridSequential()
            net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
            net.initialize()
            x = mx.nd.array(onp.random.randn(8, 16).astype("float32"))
            y = mx.nd.array(onp.random.randint(0, 8, size=(8,))
                            .astype("int32"))
            net(x)
            loss = SoftmaxCrossEntropyLoss()
            trainer = Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.1, "momentum": 0.9},
                              kvstore=None)
            step = trainer.compile_step(lambda a, b: loss(net(a), b))
            return step, x, y

        db = tuning.AutotuneCache(
            os.path.join(tempfile.mkdtemp(prefix="mx_autotune_"),
                         "autotune.json"))
        saved = tuning.space.overrides()
        try:
            backend = None
            for label in ("first run ", "second run"):
                step, x, y = build_step()
                out = tuning.tune_step(step, (x, y), mode="on",
                                       budget=3, db=db)
                backend = out.backend or backend
                hitmiss = ("HIT (replayed, 0 trials)"
                           if out.source == "cache"
                           else "MISS -> searched + persisted")
                print(f"{label}: cache {hitmiss}  trials={out.trials}"
                      f"  config={out.config or '{defaults}'}"
                      + (f"  delta={out.delta_pct}%"
                         if out.delta_pct is not None else ""))
            print(f"winning config: {out.config or '{defaults}'} "
                  f"(backend={backend}, 3-trial budget)")
        finally:
            tuning.space.clear_overrides()
            tuning.space.apply_config(saved)
    except Exception as e:  # pragma: no cover - env-dependent
        print("autotune check failed:", repr(e))


def check_serving():
    """Serving-engine health (docs/SERVING.md): AOT-compile a tiny
    predictor across its shape buckets, push a concurrent closed-loop
    burst through the dynamic batcher, and print the batcher stats
    table plus a p50/p99 latency probe — queue/coalescing/pipelining
    misconfiguration (zero batching, saturated queue, padding waste)
    is visible without a load rig."""
    print("----------Inference Serving----------")
    try:
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu import serving, telemetry
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.runtime import compile_cache_stats
        from mxnet_tpu.serving import loadgen

        import time
        onp.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(64, activation="relu", in_units=32),
                nn.Dense(8, in_units=64))
        net.initialize()
        x1 = mx.nd.array(onp.zeros((1, 32), "float32"))
        net(x1)
        buckets = (1, 2, 4, 8)
        pred = serving.CompiledPredictor(net, bucket_sizes=buckets)
        t0 = time.time()
        pred.warmup(x1)
        print("buckets      :", buckets,
              f"(AOT-compiled in {time.time() - t0:.2f}s, "
              f"{pred.n_traces} programs)")
        X = onp.random.randn(64, 32).astype("float32")
        requests, conc = 64, 4
        batcher = serving.DynamicBatcher(pred, max_batch=buckets[-1],
                                         timeout_ms=2.0)
        rep = loadgen.run_closed_loop(
            lambda i: batcher.submit(
                mx.nd.array(X[i % 64:i % 64 + 1])).result(60),
            conc, requests)
        fill = batcher.batch_fill
        stats = dict(batcher.stats)
        batcher.close()
        print(f"closed loop  : concurrency={conc} requests={requests}")
        print(f"throughput   : {rep['qps']} req/s")
        print(f"latency      : p50 {rep['p50_ms']} ms, "
              f"p99 {rep['p99_ms']} ms")
        print("-- batcher stats --")
        print(f"{'batches':<14s}{stats['batches']}")
        print(f"{'rows':<14s}{stats['rows']}")
        print(f"{'padded rows':<14s}{stats['padded_rows']}")
        print(f"{'batch fill':<14s}"
              f"{round(fill, 3) if fill is not None else None}")
        print(f"{'flush full':<14s}{stats['flush_full']}")
        print(f"{'flush timeout':<14s}{stats['flush_timeout']}")
        print(f"{'flush idle':<14s}{stats['flush_idle']}")
        print(f"{'errors':<14s}{stats['errors']}")
        lat = telemetry.registry().get(
            telemetry.names.SERVING_LATENCY)
        if lat is not None and lat.count():
            print(f"retire hist  : n={lat.count()} "
                  f"p50={lat.percentile(50) * 1e3:.2f} ms "
                  f"p99={lat.percentile(99) * 1e3:.2f} ms "
                  "(mx_serving_request_seconds)")
        cc = compile_cache_stats()
        if cc["enabled"]:
            print("compile cache:", cc["dir"],
                  f"hits={cc['hits']} misses={cc['misses']}")
        else:
            print("compile cache: off (set MXNET_COMPILE_CACHE=<dir> "
                  "to warm-start serving executables)")

        # resilience panel: one injected device revocation under a
        # small burst, served through the ServingSupervisor — breaker
        # transitions, recovery downtime, and the outcome census show
        # whether device-loss recovery is wired (docs/SERVING.md
        # "Resilient serving")
        print("-- resilience (1 injected revocation under burst) --")
        from mxnet_tpu.testing import faults

        def build():
            mx.random.seed(11)
            net2 = nn.HybridSequential()
            net2.add(nn.Dense(64, activation="relu", in_units=32),
                     nn.Dense(8, in_units=64))
            net2.initialize()
            net2(x1)
            return serving.CompiledPredictor(net2,
                                             bucket_sizes=(1, 2, 4))

        sup = serving.ServingSupervisor(build, example=(x1,),
                                        max_batch=4, timeout_ms=2.0)
        outcomes = {"ok": 0, "rejected": 0, "deadline_missed": 0,
                    "error": 0}
        try:
            faults.configure("serving.dispatch:before=2:revoke:1")
            futs = []
            for i in range(24):
                try:
                    futs.append(sup.submit(
                        mx.nd.array(X[i % 64:i % 64 + 1])))
                except Exception as e:
                    futs.append(None)
                    outcomes[loadgen.classify_outcome(e)] += 1
            for f in futs:
                if f is None:
                    continue
                try:
                    f.result(60)
                    outcomes["ok"] += 1
                except Exception as e:
                    outcomes[loadgen.classify_outcome(e)] += 1
        finally:
            faults.reset()
            sup.close()
        print("breaker      :",
              " -> ".join(s for s, _t, _c in sup.breaker.transitions))
        print(f"recoveries   : {sup.stats['recoveries']} "
              f"(downtime {sup.stats['recovery_downtime_s']:.2f} s, "
              f"requeued {sup.stats['requeued']})")
        print("outcomes     :", outcomes)
        dl = serving.default_deadline_ms()
        print("shed policy  : MXNET_SERVING_SHED="
              f"{serving.shed_mode()} deadline="
              + (f"{dl:.0f} ms" if dl is not None else "unset"))
    except Exception as e:  # pragma: no cover - env-dependent
        print("serving check failed:", repr(e))


def check_decode():
    """Continuous-batching decode health (docs/SERVING.md "Continuous
    batching"): build the reference decoder + engine, stream a small
    mixed-length burst, and print the slot table, the page-allocator
    census, and the streamed-burst latency panel — a wedged scheduler
    (starved decode batch, leaked pages, dead slots) is visible
    without a load rig."""
    print("----------Continuous-Batching Decode----------")
    try:
        import numpy as onp
        from mxnet_tpu import serving
        from mxnet_tpu.ops import kernels as _kern
        import time

        model = serving.TinyDecoder(vocab=48, d_model=32, num_heads=2,
                                    seed=0)
        eng = serving.DecodeEngine(model, ladder=(1, 2, 4),
                                   max_context=48, page_size=8,
                                   start=False)
        t0 = time.time()
        eng.warmup()
        print(f"slot ladder  : {tuple(eng._ladder)} "
              f"(decode+prefill AOT-compiled in {time.time() - t0:.2f}s)")
        print(f"prefill chunk: {eng._chunk} tokens   "
              f"page size: {eng.kv.page_size} tokens")
        rng = onp.random.RandomState(3)
        prompts = [rng.randint(0, 48, size=int(n))
                   for n in (3, 11, 5, 2, 7, 4)]
        mns = [6, 3, 12, 4, 3, 5]
        t0 = time.time()
        streams = [eng.submit(p, max_new=m)
                   for p, m in zip(prompts, mns)]
        # mid-flight slot table: run a few iterations, then look
        for _ in range(4):
            eng.step_once()
        eng.sync()
        print("-- slot table (mid-burst) --")
        print(f"{'slot':<6}{'phase':<10}{'pos':<6}{'kv_len':<8}"
              f"{'tokens':<8}pages")
        for s in range(eng.slots):
            req = eng._occupant[s]
            if req is None:
                print(f"{s:<6}{'free':<10}")
                continue
            pages = [int(p) for p in eng._table[s] if p]
            print(f"{s:<6}{req.phase:<10}{req.pos:<6}"
                  f"{int(eng._device_len[s]):<8}{req.generated:<8}"
                  f"{pages}")
        print("-- page allocator --")
        for k, v in eng.kv.stats().items():
            print(f"{k:<15}{v}")
        eng.drain()
        recs = [s.record() for s in streams]
        wall = time.time() - t0
        from mxnet_tpu.serving import loadgen
        summ = loadgen.streaming_summary(recs, wall)
        print("-- streamed burst --")
        print(f"requests     : {len(prompts)} "
              f"({sum(r['tokens'] for r in recs)} tokens, "
              f"{eng.stats['steps']} decode steps, "
              f"{eng.stats['prefill_chunks']} prefill chunks)")
        print(f"ttft         : p50 {summ['ttft_p50_ms']} ms, "
              f"p99 {summ['ttft_p99_ms']} ms")
        print(f"tpot         : p50 {summ['tpot_p50_ms']} ms, "
              f"p99 {summ['tpot_p99_ms']} ms")
        print(f"goodput      : {summ['tokens_per_sec']} tok/s")
        print(f"kv util peak : {eng.stats['kv_util_peak']:.3f}")
        path, reason = _kern.decisions().get(
            "rnn_decode_step", ("?", "never dispatched"))
        print(f"decode kernel: {path} ({reason})")
        eng.close()

        # -- speculative decode + prefix sharing panel --
        print("-- speculative decode --")
        from mxnet_tpu.serving.decode import spec_k as _sk, \
            prefix_share as _psh
        print(f"spec_k       : {_sk()} (MXNET_DECODE_SPEC_K)   "
              f"prefix_share: {int(_psh())} "
              f"(MXNET_DECODE_PREFIX_SHARE)")
        sp = serving.DecodeEngine(model, ladder=(1, 4),
                                  max_context=64, page_size=8,
                                  start=False, spec_k=4,
                                  prefix_share=True)
        sp.warmup()
        base = rng.randint(0, 48, size=20).astype(onp.int32)
        s1 = sp.submit(base, max_new=12)
        for _ in range(5):
            sp.step_once()
            sp.sync()
        more = [sp.submit(onp.concatenate(
                    [base, onp.asarray([t, 5], onp.int32)]),
                    max_new=10)
                for t in (3, 4)]
        sp.drain()
        drafter = sp._drafter
        print(f"drafter      : {type(drafter).__name__}"
              f"{getattr(drafter, 'n', '')}")
        st = sp.stats
        rate = (st['spec_accepted'] / st['spec_drafted']
                if st['spec_drafted'] else None)
        print(f"verify steps : {st['spec_steps']} "
              f"({st['spec_drafted']} drafted, "
              f"{st['spec_accepted']} accepted, rate "
              f"{rate if rate is None else round(rate, 3)})")
        hist = st["accept_hist"]
        width = max(hist.values()) if hist else 1
        for n in sorted(hist):
            bar = "#" * max(1, int(24 * hist[n] / width))
            print(f"  accept {n:>2} | {bar} {hist[n]}")
        kvs = sp.kv.stats()
        print(f"prefix cache : {st['prefix_hits']} hits "
              f"({st['prefix_tokens']} tokens skipped), "
              f"{kvs['cow_copies']} COW copies, shared-page peak "
              f"{st['kv_shared_peak']}")
        for s in (s1, *more):
            s.result()
        sp.close()
    except Exception as e:  # pragma: no cover - env-dependent
        print("decode check failed:", repr(e))


def check_fleet():
    """Serving-fleet health (docs/SERVING.md "Serving fleet"): spin a
    small multi-replica fleet on the visible devices, push a routed
    burst through the FleetRouter, revoke one replica's device
    mid-traffic, and print the per-replica census, the failover /
    restart ledger, and the mx_fleet_* metric snapshot — a fleet that
    loses accepted requests or never restarts a dead replica is
    visible without a load rig."""
    print("----------Serving Fleet----------")
    try:
        import numpy as onp
        import jax
        import mxnet_tpu as mx
        from mxnet_tpu import serving, telemetry
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.serving import loadgen
        from mxnet_tpu.testing import faults

        import time
        n_dev = len(jax.devices())
        n = min(3, n_dev)
        print(f"devices      : {n_dev} visible, fleet size {n}"
              + ("" if n > 1 else "  (single device: failover leg "
                 "needs >=2 — set XLA_FLAGS="
                 "--xla_force_host_platform_device_count=4)"))
        print("env knobs    : "
              f"MXNET_FLEET_REPLICAS={serving.fleet_replicas()} "
              f"min={serving.fleet_min_replicas()} "
              f"max={serving.fleet_max_replicas()} "
              f"scale_up_wait={serving.fleet_scale_up_wait_s() * 1e3:.0f}ms "
              f"restart_retries={serving.fleet_restart_retries()}")

        def build():
            mx.random.seed(11)
            net = nn.HybridSequential()
            net.add(nn.Dense(64, activation="relu", in_units=32),
                    nn.Dense(8, in_units=64))
            net.initialize()
            net(mx.nd.array(onp.zeros((1, 32), "float32")))
            return serving.CompiledPredictor(net, bucket_sizes=(1, 2, 4))

        x1 = mx.nd.array(onp.zeros((1, 32), "float32"))
        t0 = time.time()
        fleet = serving.FleetController(build, example=(x1,),
                                        replicas=n, max_batch=4,
                                        timeout_ms=2.0)
        print(f"spawn        : {n} replica(s) warm in "
              f"{time.time() - t0:.2f}s "
              f"({[r.device.id for r in fleet.replicas]})")
        onp.random.seed(0)
        X = onp.random.randn(64, 32).astype("float32")
        victim = fleet.replicas[-1]
        try:
            if n > 1:
                # one targeted device revocation two dispatches into
                # the burst: the fleet must failover the victim's
                # backlog and restart it on a spare (or same) device
                faults.configure(
                    f"serving.dispatch@{victim.name}:before=2"
                    f":revoke:d{victim.device.id}")
            rep = loadgen.run_closed_loop(
                loadgen.fleet_issue(
                    fleet.router,
                    lambda i: (mx.nd.array(X[i % 64:i % 64 + 1]),),
                    timeout=60),
                concurrency=4, requests=32)
        finally:
            faults.reset()
        if n > 1:
            deadline = time.time() + 15
            while time.time() < deadline and not any(
                    e.kind in ("restart", "restart_failed")
                    for e in fleet.events):
                time.sleep(0.05)
        print(f"routed burst : 32 requests, concurrency 4 -> "
              f"{rep['qps']} req/s "
              f"(p50 {rep['p50_ms']} ms, p99 {rep['p99_ms']} ms)")
        print("outcomes     :", rep["outcomes"])
        for name, r in sorted(rep.get("replicas", {}).items()):
            print(f"  {name:<12}: {r['qps']} req/s  {r['outcomes']}")
        st = fleet.stats
        print(f"failover     : failovers={st['failovers']} "
              f"requeued={st['requeued']} restarts={st['restarts']} "
              f"failed_requeues={st['failed_requeues']}")
        kinds = [f"{e.kind}({e.replica})" for e in fleet.events
                 if e.kind not in ("spawn",)]
        if kinds:
            print("events       :", " -> ".join(kinds))
        print("-- replica table --")
        print(f"{'replica':<12}{'state':<12}{'device':<14}"
              f"{'version':<9}queued")
        for r in fleet.describe()["replicas"]:
            print(f"{r['name']:<12}{r['state']:<12}"
                  f"{str(r['device']):<14}{r['version']:<9}"
                  f"{r['queued']}")
        routed = telemetry.registry().get(telemetry.names.FLEET_ROUTED)
        if routed is not None:
            print(f"{telemetry.names.FLEET_ROUTED}:",
                  dict(sorted(routed.values().items())))
        wait = telemetry.registry().get(
            telemetry.names.FLEET_QUEUE_WAIT)
        if wait is not None and wait.count():
            print(f"{telemetry.names.FLEET_QUEUE_WAIT}   : "
                  f"n={wait.count()} "
                  f"p50={wait.percentile(50) * 1e3:.2f} ms "
                  f"p99={wait.percentile(99) * 1e3:.2f} ms")
        fleet.close()
    except Exception as e:  # pragma: no cover - env-dependent
        print("fleet check failed:", repr(e))


def check_threads():
    """Concurrency-audit panel (docs/ANALYSIS.md "Concurrency
    analysis"): the live audited-lock table, the observed lock-order
    graph with its cycle status, a planted two-lock inversion demo on
    a PRIVATE graph (so the demo never pollutes the process-global
    hierarchy), and a brief contention snapshot under a deliberately
    held lock — lock-order bugs and stalls are visible without
    attaching a debugger."""
    print("----------Concurrency Audit----------")
    try:
        import threading
        import time

        from mxnet_tpu import serving, telemetry  # noqa: F401 - wires locks
        from mxnet_tpu.analysis import threads

        print(f"env knobs    : MXNET_LOCK_STALL_SEC="
              f"{threads.stall_seconds():g} "
              f"MXNET_THREADS_DUMP_DIR={threads.dump_dir() or '<unset>'}")
        locks = threads.describe_locks()
        print(f"-- audited locks ({len(locks)} name(s)) --")
        print(f"{'name':<28}{'kind':<7}{'inst':<6}{'held':<6}"
              f"{'waiters':<9}owner")
        for l in locks:
            print(f"{l['name']:<28}{l['kind']:<7}{l['instances']:<6}"
                  f"{l['held']:<6}{l['waiters']:<9}{l['owner'] or '-'}")
        edges = threads.graph().edges()
        cycles = threads.find_cycles()
        print(f"order graph  : {len(edges)} edge(s), "
              f"{len(cycles)} cycle(s)"
              + ("  <- POTENTIAL DEADLOCK" if cycles else ""))
        for e in sorted(edges, key=lambda e: (e['from'], e['to']))[:12]:
            print(f"  {e['from']} -> {e['to']}  (x{e['count']}, "
                  f"thread {e['thread']})")
        if len(edges) > 12:
            print(f"  ... and {len(edges) - 12} more")

        # planted inversion demo on a PRIVATE graph: what a real
        # lock-cycle finding looks like, without touching the global
        # hierarchy the tier-1 baseline sweep audits
        demo = threads.LockOrderGraph()
        a = threads.mx_lock("demo.inversion.a", graph=demo)
        b = threads.mx_lock("demo.inversion.b", graph=demo)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        findings = threads.cycle_findings(demo)
        print(f"-- planted inversion demo ({len(findings)} finding) --")
        for f in findings:
            print(" ", str(f)[:240])

        # contention snapshot: hold a probe lock, let one waiter block,
        # and show the waiter/longest-wait census the dump would rank
        probe = threads.mx_lock("demo.contention")
        seen = threading.Event()

        def waiter():
            seen.set()
            with probe:
                pass

        with probe:
            t = threading.Thread(target=waiter, name="demo-waiter",
                                 daemon=True)
            t.start()
            seen.wait(1.0)
            time.sleep(0.15)     # let the waiter enter its timed poll
            row = [l for l in threads.describe_locks()
                   if l["name"] == "demo.contention"]
            if row:
                print(f"-- contention snapshot --")
                print(f"demo.contention: held by {row[0]['owner']!r}, "
                      f"{row[0]['waiters']} waiter(s), longest wait "
                      f"{row[0]['longest_wait_s'] * 1e3:.0f} ms")
        t.join(2.0)
        wait_h = telemetry.registry().get(
            telemetry.names.THREADS_LOCK_WAIT)
        if wait_h is not None and wait_h.count():
            print(f"{telemetry.names.THREADS_LOCK_WAIT}: "
                  f"n={wait_h.count()} "
                  f"p99={wait_h.percentile(99) * 1e3:.2f} ms")
    except Exception as e:  # pragma: no cover - env-dependent
        print("threads check failed:", repr(e))


def check_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("version      :", platform.version())
    print("----------Hardware Info----------")
    print("machine      :", platform.machine())
    print("processor    :", platform.processor())
    if sys.platform.startswith("linux"):
        try:
            out = subprocess.run(["lscpu"], capture_output=True,
                                 text=True, timeout=10).stdout
            for line in out.splitlines():
                if any(k in line for k in ("Model name", "CPU(s)",
                                           "Thread", "Socket")):
                    print(line)
        except Exception:
            pass


def check_environment():
    print("----------Environment----------")
    for k, v in sorted(os.environ.items()):
        if k.startswith(("MXNET_", "OMP_", "KMP_", "XLA_", "JAX_",
                         "LIBJPEG_", "TPU_")):
            print(f"{k}=\"{v}\"")


def check_network(timeout):
    # kept for reference parity; default-off because target
    # environments have no egress
    import socket
    print("----------Network Test----------")
    urls = {"MXNet github": "github.com",
            "PYPI": "pypi.python.org"}
    for name, host in urls.items():
        try:
            socket.setdefaulttimeout(timeout)
            socket.gethostbyname(host)
            print(f"DNS {name} ({host}): ok")
        except Exception as e:
            print(f"DNS {name} ({host}): FAILED ({e})")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Diagnose the runtime environment")
    parser.add_argument("--network", action="store_true",
                        help="also run DNS connectivity checks "
                        "(off by default: egress-less environments)")
    parser.add_argument("--analysis", action="store_true",
                        help="also compile a tiny MLP train step and "
                        "print its mx.analysis ProgramReport "
                        "(collectives, donation, host transfers)")
    parser.add_argument("--engine", action="store_true",
                        help="also run a tiny pipelined TrainLoop and "
                        "print async-dispatch stats (in-flight window, "
                        "syncs per 100 steps, prefetch depth/starvation)")
    parser.add_argument("--telemetry", action="store_true",
                        help="also run a tiny pipelined TrainLoop with "
                        "telemetry on and print the metrics-registry "
                        "snapshot, a 10-step phase-timeline summary "
                        "(p50/p99), and the MFU estimate")
    parser.add_argument("--memory", action="store_true",
                        help="also compile a tiny train step and print "
                        "its memory report, the live-buffer census by "
                        "pool (+ untracked reconciliation), per-device "
                        "allocator stats, and the memory-budget status")
    parser.add_argument("--numerics", action="store_true",
                        help="also run a tiny numerics-instrumented "
                        "train step: 10-step grad/param-norm table plus "
                        "a simulated-divergence demo (one anomaly, "
                        "NaN-origin forensics, post-mortem dump)")
    parser.add_argument("--fusion", action="store_true",
                        help="also audit XLA's fusion decisions for a "
                        "tiny MLP and the LSTM-LM example: kernel "
                        "table (kind/ops/FLOPs/boundary bytes/bound "
                        "class) plus top stranded ops")
    parser.add_argument("--sharding", action="store_true",
                        help="also compile the zero-sharded MLP on the "
                        "virtual dp mesh and print its sharding-flow "
                        "table, top implicit reshards, and per-axis "
                        "communication cost estimate")
    parser.add_argument("--overlap", action="store_true",
                        help="also compile the zero-sharded adam MLP "
                        "serial vs bucketed on the virtual dp mesh and "
                        "print each schedule's per-collective overlap "
                        "windows and exposed-comm fractions")
    parser.add_argument("--kernels", action="store_true",
                        help="also print the Pallas kernel layer's "
                        "per-kernel dispatch decisions (pallas/"
                        "interpret/xla + reason) and an interpret-vs-"
                        "xla parity probe for a tiny LSTM scan and "
                        "LayerNorm")
    parser.add_argument("--autotune", action="store_true",
                        help="also print the registered tunable table "
                        "and run a 3-trial analytical autotune sweep "
                        "on a tiny MLP, showing the winning config and "
                        "the cache miss->hit round trip")
    parser.add_argument("--serving", action="store_true",
                        help="also AOT-compile a tiny bucketed "
                        "predictor, run a concurrent burst through the "
                        "dynamic batcher, and print the batcher stats "
                        "table plus a p50/p99 latency probe")
    parser.add_argument("--decode", action="store_true",
                        help="also build the continuous-batching "
                        "decode engine, stream a mixed-length burst, "
                        "and print the slot table, page-allocator "
                        "census, and TTFT/TPOT panel")
    parser.add_argument("--fleet", action="store_true",
                        help="also spin a small multi-replica serving "
                        "fleet, route a burst (with one injected "
                        "replica-device revocation when >=2 devices "
                        "are visible), and print the per-replica "
                        "census, failover/restart ledger, and "
                        "mx_fleet_* metric snapshot")
    parser.add_argument("--threads", action="store_true",
                        help="also print the concurrency-audit panel: "
                        "live audited-lock table, observed lock-order "
                        "graph + cycle status, a planted two-lock "
                        "inversion demo (private graph), and a "
                        "contention snapshot")
    parser.add_argument("--elastic", action="store_true",
                        help="also run a tiny supervised TrainLoop, "
                        "inject one mid-run fault (device revocation / "
                        "transient error), and print the RecoveryLog "
                        "table and restore provenance")
    parser.add_argument("--timeout", type=int, default=10)
    args = parser.parse_args(argv)
    check_python()
    check_pip()
    check_mxnet()
    check_accelerator()
    if args.analysis:
        check_analysis()
    if args.engine:
        check_engine()
    if args.telemetry:
        check_telemetry()
    if args.memory:
        check_memory()
    if args.numerics:
        check_numerics()
    if args.fusion:
        check_fusion()
    if args.sharding:
        check_sharding()
    if args.overlap:
        check_overlap()
    if args.kernels:
        check_kernels()
    if args.autotune:
        check_autotune()
    if args.serving:
        check_serving()
    if args.decode:
        check_decode()
    if args.fleet:
        check_fleet()
    if args.threads:
        check_threads()
    if args.elastic:
        check_elastic()
    check_os()
    check_environment()
    if args.network:
        check_network(args.timeout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
