#!/usr/bin/env python
"""Measure kvstore aggregate push/pull bandwidth on model-shaped arrays.

Reference analog: tools/bandwidth/measure.py — same experiment (push a
network's gradient set through a kvstore, pull it back, report GB/s and
the error vs a serial reduction), re-targeted at this framework's
kvstore types ('local', 'tpu', 'dist*') instead of GPU device lists.
The dist cross-process path has its own artifact-producing rig in
benchmark/dist_kvbench.py; this tool is the interactive single-process
view of the same transfer path.
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="command for benchmark kvstore bandwidth")
    parser.add_argument("--network", type=str, default="resnet18_v1",
                        help="gluon model_zoo.vision model whose "
                        "parameter shapes are pushed")
    parser.add_argument("--kv-store", type=str, default="tpu",
                        help="the kvstore type: local | tpu | dist_sync")
    parser.add_argument("--num-batches", type=int, default=5)
    parser.add_argument("--disp-batches", type=int, default=1)
    parser.add_argument("--test-results", type=int, default=1,
                        help="whether to check reduction correctness")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--optimizer", type=str, default="None",
                        help="optimizer applied inside the kvstore; "
                        "None means plain reduce")
    parser.add_argument("--gc-type", type=str, default="none",
                        help="gradient compression: none | 2bit | 1bit")
    args = parser.parse_args(argv)
    logging.info(args)
    return args


def get_shapes(network, num_classes):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    net = getattr(vision, network)(classes=num_classes)
    net.initialize()
    net(mx.nd.array(np.zeros((1, 3, 32, 32), "float32")))
    return [tuple(p.shape) for p in net.collect_params().values()
            if p._data is not None and p.grad_req != "null"]


def error(result, expected):
    num = sum(float(np.abs(r.asnumpy() - e).sum()) for r, e in
              zip(result, expected))
    den = sum(float(np.abs(e).sum()) for e in expected)
    return num / max(den, 1e-12)


def run(args):
    import mxnet_tpu as mx

    kv = mx.kvstore.create(args.kv_store)
    if args.gc_type != "none":
        kv.set_gradient_compression({"type": args.gc_type})
    if args.optimizer not in (None, "None"):
        kv.set_optimizer(mx.optimizer.create(args.optimizer))

    shapes = get_shapes(args.network, args.num_classes)
    size = sum(int(np.prod(s)) for s in shapes)
    rng = np.random.RandomState(0)
    grads = [mx.nd.array(rng.uniform(-1, 1, s).astype("float32"))
             for s in shapes]
    outs = [mx.nd.zeros(s) for s in shapes]
    keys = list(range(len(shapes)))
    for k, g in zip(keys, grads):
        kv.init(k, mx.nd.zeros(g.shape))

    # bytes moved per batch: one push + one pull of every array
    nbytes = 2 * 4 * size
    times = []
    for b in range(args.num_batches):
        t0 = time.perf_counter()
        for k, g, o in zip(keys, grads, outs):
            kv.push(k, g)
            kv.pull(k, out=o)
        outs[-1].asnumpy()  # host sync
        dt = time.perf_counter() - t0
        times.append(dt)
        if (b + 1) % args.disp_batches == 0:
            logging.info("batch %d: %.3f s, %.2f GB/s",
                         b, dt, nbytes / dt / 1e9)

    if args.test_results and args.optimizer in (None, "None") and \
            args.gc_type == "none":
        expected = [g.asnumpy() * kv.num_workers for g in grads]
        err = error(outs, expected)
        logging.info("reduction error: %.2e", err)
        assert err < 1e-5, f"kvstore reduction mismatch: {err}"

    best = min(times)
    result = {"network": args.network, "kv_store": args.kv_store,
              "params_mb": round(size * 4 / 1e6, 1),
              "best_sec_per_batch": round(best, 4),
              "gbps": round(nbytes / best / 1e9, 2)}
    logging.info("result: %s", result)
    return result


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    run(parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
