#!/usr/bin/env python
"""Multi-process / multi-host job launcher (reference: tools/launch.py —
dmlc-tracker submitting N workers + servers + scheduler over
local/ssh/mpi/sge/yarn).

TPU-native redesign: there are no parameter servers — every process is an
SPMD worker in one global mesh (`jax.distributed`). The launcher keeps the
reference CLI (`-n` workers, `--launcher local|ssh`) and env-var contract
(DMLC_NUM_WORKER / DMLC_WORKER_ID / DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT,
consumed by mxnet_tpu.parallel.dist.initialize), so reference launch
scripts port unchanged:

    python tools/launch.py -n 4 --launcher local python train.py
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys


def launch_local(n: int, cmd, port: int) -> int:
    """Spawn n local worker processes sharing a coordinator (the analog of
    the reference's `--launcher local` multi-process rig used by
    tests/nightly/dist_sync_kvstore.py)."""
    procs = []
    for i in range(n):
        env = dict(os.environ)
        env.update({
            "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(i),
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
        })
        procs.append(subprocess.Popen(cmd, env=env))

    def _kill(*_):
        for p in procs:
            p.terminate()
        sys.exit(1)

    signal.signal(signal.SIGINT, _kill)
    signal.signal(signal.SIGTERM, _kill)
    return _wait_all(procs)


def launch_ssh(n: int, cmd, hostfile: str, port: int) -> int:
    """One worker per host line in ``hostfile`` (reference ssh launcher)."""
    with open(hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    if len(hosts) < n:
        raise SystemExit(f"hostfile has {len(hosts)} hosts, need {n}")
    coord = hosts[0]
    procs = []
    for i in range(n):
        envs = " ".join([
            f"DMLC_NUM_WORKER={n}", f"DMLC_WORKER_ID={i}",
            "DMLC_ROLE=worker", f"DMLC_PS_ROOT_URI={coord}",
            f"DMLC_PS_ROOT_PORT={port}",
        ])
        remote = f"cd {shlex.quote(os.getcwd())} && {envs} " + \
            " ".join(shlex.quote(c) for c in cmd)
        # -t allocates a PTY so killing the ssh client sends SIGHUP to the
        # remote command instead of orphaning it on every host
        procs.append(subprocess.Popen(["ssh", "-tt", "-o",
                                       "StrictHostKeyChecking=no",
                                       hosts[i], remote]))

    def _kill(*_):
        for p in procs:
            p.terminate()
        sys.exit(1)

    signal.signal(signal.SIGINT, _kill)
    signal.signal(signal.SIGTERM, _kill)
    return _wait_all(procs)


def _wait_all(procs) -> int:
    """Wait on all workers; when one fails, terminate the siblings (they
    may be blocked in a collective waiting for the dead rank forever)."""
    import time
    rc = 0
    alive = list(procs)
    while alive:
        for p in list(alive):
            r = p.poll()
            if r is None:
                continue
            alive.remove(p)
            if r != 0:
                rc = rc or r
                for q in alive:
                    q.terminate()
        time.sleep(0.05)
    return rc


def main():
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", choices=["local", "ssh"], default="local")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("-p", "--port", type=int, default=9091)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    if args.launcher == "local":
        rc = launch_local(args.num_workers, args.command, args.port)
    else:
        if not args.hostfile:
            ap.error("--launcher ssh requires --hostfile")
        rc = launch_ssh(args.num_workers, args.command, args.hostfile,
                        args.port)
    sys.exit(rc)


if __name__ == "__main__":
    main()
