#!/usr/bin/env python
"""Multi-process / multi-host job launcher (reference: tools/launch.py —
dmlc-tracker submitting N workers + servers + scheduler over
local/ssh/mpi/sge/yarn).

TPU-native redesign: there are no parameter servers — every process is an
SPMD worker in one global mesh (`jax.distributed`). The launcher keeps the
reference CLI (`-n` workers, `--launcher local|ssh`) and env-var contract
(DMLC_NUM_WORKER / DMLC_WORKER_ID / DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT,
consumed by mxnet_tpu.parallel.dist.initialize), so reference launch
scripts port unchanged:

    python tools/launch.py -n 4 --launcher local python train.py
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys


def _may_own_accelerator(env) -> bool:
    """True when the child could hold the accelerator client. Killing a
    process mid-TPU-dispatch can wedge a tunneled relay for HOURS (it
    cost round 3 both driver artifacts) — such processes must exit on
    SIGTERM, never SIGKILL."""
    return env.get("JAX_PLATFORMS", "").lower() != "cpu"


def _graceful_stop(procs, owns_accel, grace=None) -> None:
    """Dead-rank cleanup protocol: SIGTERM -> grace window -> SIGKILL,
    where the SIGKILL escalation is PER-PROCESS gated: CPU-pinned
    stragglers are hard-killed, accelerator-owning stragglers only ever
    receive repeated SIGTERM + a loud warning (kill-hygiene protocol,
    docs/PERF_NOTES.md)."""
    import time
    if grace is None:
        grace = float(os.environ.get("MXNET_LAUNCH_KILL_GRACE", "10"))
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace
    while time.time() < deadline:
        if all(p.poll() is not None for p in procs):
            return
        time.sleep(0.1)
    stragglers = []
    for p, owns in zip(procs, owns_accel):
        if p.poll() is None:
            if owns:
                print(f"launch: worker pid {p.pid} may own the "
                      "accelerator; NOT hard-killing (a SIGKILL "
                      "mid-dispatch can wedge the device relay). "
                      "Re-sending SIGTERM.", file=sys.stderr)
                p.terminate()
                stragglers.append(p)
            else:
                p.kill()
    # bounded supervision of accelerator-owning stragglers: keep
    # re-sending SIGTERM once per grace window rather than orphaning
    # them after a single resend
    for attempt in range(5):
        stragglers = [p for p in stragglers if p.poll() is None]
        if not stragglers:
            return
        time.sleep(grace)
        for p in stragglers:
            if p.poll() is None:
                print(f"launch: pid {p.pid} still alive after "
                      f"{attempt + 2} SIGTERMs; re-sending.",
                      file=sys.stderr)
                p.terminate()
    stragglers = [p for p in stragglers if p.poll() is None]
    if stragglers:
        print("launch: giving up on accelerator-owning stragglers "
              f"{[p.pid for p in stragglers]}; they keep SIGTERM "
              "semantics (never SIGKILLed) — clean up manually if the "
              "device relay stays held.", file=sys.stderr)


def launch_local(n: int, cmd, port: int) -> int:
    """Spawn n local worker processes sharing a coordinator (the analog of
    the reference's `--launcher local` multi-process rig used by
    tests/nightly/dist_sync_kvstore.py)."""
    procs = []
    owns = []
    for i in range(n):
        env = dict(os.environ)
        env.update({
            "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(i),
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
        })
        procs.append(subprocess.Popen(cmd, env=env))
        owns.append(_may_own_accelerator(env))

    def _kill(*_):
        _graceful_stop(procs, owns)
        sys.exit(1)

    signal.signal(signal.SIGINT, _kill)
    signal.signal(signal.SIGTERM, _kill)
    return _wait_all(procs, owns)


def launch_ssh(n: int, cmd, hostfile: str, port: int) -> int:
    """One worker per host line in ``hostfile`` (reference ssh launcher)."""
    with open(hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    if len(hosts) < n:
        raise SystemExit(f"hostfile has {len(hosts)} hosts, need {n}")
    coord = hosts[0]
    procs = []
    for i in range(n):
        envs = " ".join([
            f"DMLC_NUM_WORKER={n}", f"DMLC_WORKER_ID={i}",
            "DMLC_ROLE=worker", f"DMLC_PS_ROOT_URI={coord}",
            f"DMLC_PS_ROOT_PORT={port}",
        ])
        remote = f"cd {shlex.quote(os.getcwd())} && {envs} " + \
            " ".join(shlex.quote(c) for c in cmd)
        # -t allocates a PTY so killing the ssh client sends SIGHUP to the
        # remote command instead of orphaning it on every host
        procs.append(subprocess.Popen(["ssh", "-tt", "-o",
                                       "StrictHostKeyChecking=no",
                                       hosts[i], remote]))

    # the local ssh client processes never own this host's accelerator
    owns = [False] * len(procs)

    def _kill(*_):
        _graceful_stop(procs, owns)
        sys.exit(1)

    signal.signal(signal.SIGINT, _kill)
    signal.signal(signal.SIGTERM, _kill)
    return _wait_all(procs, owns)


def _wait_all(procs, owns_accel) -> int:
    """Wait on all workers; when one fails, gracefully stop the siblings
    (they may be blocked in a collective waiting for the dead rank
    forever). Escalation is SIGTERM -> grace -> SIGKILL, never
    hard-killing an accelerator-owning process (_graceful_stop)."""
    import time
    rc = 0
    alive = list(procs)
    while alive:
        for p in list(alive):
            r = p.poll()
            if r is None:
                continue
            alive.remove(p)
            if r != 0:
                rc = rc or r
                _graceful_stop(procs, owns_accel)
        time.sleep(0.05)
    return rc


def main():
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", choices=["local", "ssh"], default="local")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("-p", "--port", type=int, default=9091)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    if args.launcher == "local":
        rc = launch_local(args.num_workers, args.command, args.port)
    else:
        if not args.hostfile:
            ap.error("--launcher ssh requires --hostfile")
        rc = launch_ssh(args.num_workers, args.command, args.hostfile,
                        args.port)
    sys.exit(rc)


if __name__ == "__main__":
    main()
