#!/usr/bin/env python
"""Create a random-access index for an existing RecordIO file.

Reference analog: tools/rec2idx.py (IndexCreator over MXRecordIO).
Reads the .rec sequentially, records each record's byte offset, writes
the text index ("key\\tpos" lines) that MXIndexedRecordIO consumes.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio


class IndexCreator(recordio.MXRecordIO):
    """Reads RecordIO data and creates the index file enabling random
    access (reference rec2idx.py:26)."""

    def __init__(self, uri, idx_path, key_type=int):
        self.key_type = key_type
        self.fidx = None
        self.idx_path = idx_path
        super().__init__(uri, "r")

    def open(self):
        super().open()
        self.fidx = open(self.idx_path, "w")

    def close(self):
        super().close()
        if self.fidx is not None and not self.fidx.closed:
            self.fidx.close()

    def tell(self):
        return self._rec.tell()

    def create_index(self, key=0):
        self.reset()
        counter = 0
        pre_time = __import__("time").time()
        while True:
            now = __import__("time").time()
            if now - pre_time > 1:
                pre_time = now
                print(f"time: {now}  count: {counter}", file=sys.stderr)
            pos = self.tell()
            cont = self.read()
            if cont is None:
                break
            key = self.key_type(counter)
            self.fidx.write(f"{key}\t{pos}\n")
            counter += 1
        self.fidx.flush()
        return counter


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Create an index file from a RecordIO file")
    parser.add_argument("record", help="path to the .rec file")
    parser.add_argument("index", help="path of the index file to create")
    args = parser.parse_args(argv)
    creator = IndexCreator(args.record, args.index)
    n = creator.create_index()
    creator.close()
    print(f"indexed {n} records -> {args.index}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
