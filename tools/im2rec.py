#!/usr/bin/env python
"""Build RecordIO datasets from image folders/lists (reference:
tools/im2rec.py — list generation + multiprocess pack into .rec/.idx).

Usage (same shape as the reference):
    python tools/im2rec.py --list prefix image_root   # writes prefix.lst
    python tools/im2rec.py prefix image_root          # writes prefix.rec/.idx
List lines: "index\\tlabel\\trelative/path.jpg".
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(prefix: str, root: str, shuffle: bool = True):
    """Scan ``root``: each subdirectory is a class (reference list_image)."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    entries = []
    if classes:
        for label, cls in enumerate(classes):
            for dirpath, _, files in os.walk(os.path.join(root, cls)):
                for fn in sorted(files):
                    if fn.lower().endswith(EXTS):
                        rel = os.path.relpath(os.path.join(dirpath, fn), root)
                        entries.append((label, rel))
    else:
        for dirpath, _, files in os.walk(root):
            for fn in sorted(files):
                if fn.lower().endswith(EXTS):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    entries.append((0, rel))
    if shuffle:
        random.shuffle(entries)
    with open(prefix + ".lst", "w") as f:
        for i, (label, rel) in enumerate(entries):
            f.write(f"{i}\t{label}\t{rel}\n")
    return len(entries)


def read_list(path_lst: str):
    with open(path_lst) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) >= 3:
                yield int(parts[0]), float(parts[1]), parts[2]


def make_rec(prefix: str, root: str):
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    for idx, label, rel in read_list(prefix + ".lst"):
        with open(os.path.join(root, rel), "rb") as f:
            payload = f.read()
        hdr = recordio.IRHeader(flag=0, label=label, id=idx, id2=0)
        rec.write_idx(idx, recordio.pack(hdr, payload))
        n += 1
        if n % 1000 == 0:
            print(f"packed {n} images", file=sys.stderr)
    rec.close()
    return n


def main():
    ap = argparse.ArgumentParser(description="image folder -> RecordIO")
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="generate the .lst only")
    ap.add_argument("--no-shuffle", action="store_true")
    args = ap.parse_args()
    if args.list:
        n = make_list(args.prefix, args.root, shuffle=not args.no_shuffle)
        print(f"wrote {args.prefix}.lst ({n} images)")
    else:
        if not os.path.exists(args.prefix + ".lst"):
            make_list(args.prefix, args.root, shuffle=not args.no_shuffle)
        n = make_rec(args.prefix, args.root)
        print(f"wrote {args.prefix}.rec/.idx ({n} records)")


if __name__ == "__main__":
    main()
