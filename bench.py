#!/usr/bin/env python
"""Headline benchmark: Gluon ResNet-50 training throughput + efficiency.

Baseline: reference MXNet-CUDA ResNet-50 training, bs=128 on V100 =
363.69 img/s (docs/static_site/src/pages/api/faq/perf.md:254; BASELINE.md).
The driver runs this on one real TPU chip; vs_baseline is img/s-per-chip
against the V100 row, per BASELINE.json's north star.

Prints ONE JSON line with the primary metric plus efficiency fields:
  {"metric": "resnet50_v1_train_img_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": N, "dtype": "bf16", "tflops": N, "mfu": N,
   "bert_tokens_per_sec": N, "bert_tflops": N, "bert_mfu": N,
   "matmul_roofline_tflops": N, "peak_tflops": N, "device": "..."}

- tflops    = FLOPs actually executed per second: XLA's cost_analysis of
              the one compiled train step (fwd + bwd + update — the whole
              program the chip runs) / 1e12. Note this is the compiled-
              program count, not the "3x forward" analytic convention;
              it is the honest numerator for what the silicon does.
- mfu       = tflops / peak_tflops for the detected TPU generation.
- matmul_roofline_tflops = achieved bf16 GEMM rate of a large square
              matmul on the same chip — the practical ceiling the model
              competes against (distinguishes "framework leaves perf on
              the table" from "platform caps throughput").

The whole training step (forward, loss, backward, SGD-momentum update) is one
donated-buffer XLA computation — the TPU-native answer to the reference's
CachedOp static_alloc + bulking + fused multi_sgd (SURVEY §3.2/§3.4). Since
PR 1 the resnet/bert/lstm legs build that program through the FRAMEWORK
(gluon.TrainLoop over Trainer.compile_step, gluon/fused_step.py) rather than
the bespoke make_train_step sidecar — the bench measures the product path.

AMP note: ``mx.amp.init()`` is enabled AFTER the eager shape-materializing
forward and applies inside the jitted step (one compile). bf16 then FLOWS
between ops (amp/__init__.py), halving HBM activation traffic — the lever
the reference's fp16 row pulls on V100 (perf.md:196,210).

MXNET_BENCH_MODEL=resnet50|bert runs one model only (bert skips the
resnet fields and vice versa); default "all" runs both and emits the
combined line. MXNET_BENCH_DTYPE=fp32 disables AMP.
"""
import json
import os
import sys
import time

import numpy as onp

import jax
import jax.numpy as jnp

BASELINE_IMG_S = 363.69  # V100 fp32 training, bs=128

# bf16 peak TFLOP/s per chip by device_kind substring (public specs).
_PEAK_BF16 = [
    ("v5 lite", 197.0), ("v5litepod", 197.0), ("v5e", 197.0),
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v5p", 459.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _flush(x):
    """Force execution to finish: host-fetch one element (the only reliable
    flush on tunneled platforms where block_until_ready can return before
    execution)."""
    return float(jnp.reshape(x, (-1,))[0])


def peak_tflops():
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if jax.default_backend() == "cpu":
        return None, kind or "cpu"
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak, kind
    return None, kind


def compile_step(step_fn, *args):
    """AOT-compile the train step ONCE; return (callable, flops). The same
    executable drives the timed loop — no second jit compile just to read
    cost_analysis (compiles dominate bench startup on tunneled TPU)."""
    jitted = jax.jit(step_fn, donate_argnums=(0, 1))
    try:
        comp = jitted.lower(*args).compile()
    except Exception as e:  # pragma: no cover - platform-dependent
        log(f"bench: AOT lower/compile unavailable ({type(e).__name__}); "
            "falling back to jit")
        return jitted, None
    flops = None
    try:
        ca = comp.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        f = float(ca.get("flops", 0.0))
        flops = f if f > 0 else None
    except Exception as e:  # pragma: no cover - platform-dependent
        log(f"bench: cost_analysis unavailable ({type(e).__name__})")
    return comp, flops


def _kernel_path():
    """{kernel: pallas|interpret|xla} under the live env/backend
    (ops/kernels dispatch gate)."""
    try:
        from mxnet_tpu.ops import kernels as _k
        return _k.dispatch_table()
    except Exception:  # pragma: no cover - must not kill a bench
        return None


def framework_loop(net, lr, momentum=0.9):
    """The PRODUCT train-step path: gluon.TrainLoop over
    Trainer.compile_step — forward+backward+update as ONE donated-buffer
    XLA program built by the framework itself. The resnet/bert/lstm legs
    run through this (previously a bespoke make_train_step sidecar in
    __graft_entry__ — the bench now measures what users get)."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    trainer = mx.gluon.Trainer(
        net.collect_params(), "sgd",
        {"learning_rate": lr, "momentum": momentum}, kvstore=None)
    return mx.gluon.TrainLoop(net, trainer, SoftmaxCrossEntropyLoss())


def analyze_framework_step(tag, loop, x_nd, y_nd):
    """Structural fingerprint of the compiled step for the BENCH json:
    n_traces, collective census, donated bytes, copied-donation and
    host-transfer counts (mx.analysis program lint). A perf regression
    then ships WITH its structural diff — e.g. img/s dropped AND
    donated_bytes went to 0 says "donation broke", not just "slower"."""
    try:
        report = loop.compiled_step.analyze(x_nd, y_nd)
    except Exception as e:  # pragma: no cover - analysis must not kill
        log(f"bench[{tag}]: program analysis unavailable "
            f"({type(e).__name__}: {e})")
        return None
    d = report.to_dict()
    out = {"mode": d["mode"], "n_traces": d["n_traces"],
           "collectives": d["collectives"],
           "donated_bytes": d["donated_bytes"],
           "donation_copied": len(report.donation.copied),
           "host_transfers": d["host_transfers"],
           "dtype_drift": d["dtype_drift"],
           # fusion posture next to MFU (docs/ANALYSIS.md "Fusion
           # census"): the pending hardware re-capture records these
           # as the per-leg baselines the regression gate bands around
           "fusion": d["fusion"],
           # sharding posture (docs/ANALYSIS.md "Sharding analysis"):
           # {implicit_reshards, reshard_bytes, comm_cost_est_s,
           # sharding_table_digest} — a perf regression on a sharded
           # leg ships with its reshard diff, and the digest pins
           # whether two captures laid buffers out identically
           "sharding": d["sharding"],
           # exposed-comm posture next to comm_cost_est_s
           # (docs/PERF_NOTES.md "Communication overlap"):
           # {exposed_comm_s, overlap_fraction, zero_bucket_bytes, ...}
           # — a perf delta on a sharded leg says whether collectives
           # were hidden behind compute, not just how many bytes moved
           "overlap": d["overlap"],
           # which implementation produced this number: per-kernel
           # MXNET_PALLAS dispatch (pallas/interpret/xla) — a perf
           # delta between captures must name its kernel path
           "kernel_path": _kernel_path()}
    # autotune posture (docs/PERF_NOTES.md "Autotuner"): the legs run
    # under MXNET_AUTOTUNE=cached, so a capture records WHICH tuned
    # config (if any) produced its numbers, how many trials it cost
    # (0 on replay), and the tuner's estimated win over the defaults —
    # the next hardware re-capture ships its tuning provenance
    at = getattr(loop.compiled_step, "autotune_result", None)
    out.update(at.bench_dict() if at is not None else
               {"autotune_config": None, "autotune_trials": None,
                "autotune_delta_pct": None})
    log(f"bench[{tag}]: analysis {out}")
    return out


def numerics_probe(tag, loop, x_nd, y_nd, steps=6):
    """Numerics-domain fingerprint + overhead for one leg
    (docs/OBSERVABILITY.md "numerics"): re-time a short pipelined loop
    with numerics OFF, switch the step to MXNET_NUMERICS=global (one
    extra compile for the instrumented bucket — the mode is part of the
    cache signature), time again, and report {grad_norm_final,
    update_ratio, nonfinite_events, numerics_overhead_pct}. The main
    timed loop above keeps its numbers untouched."""
    from mxnet_tpu import telemetry
    step = loop.compiled_step
    if step.mode != "fused":
        return None
    prev_mode = step.numerics

    def timed():
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = loop.step(x_nd, y_nd)
        loop.synchronize()
        _flush(loss._data)
        return (time.perf_counter() - t0) / steps

    try:
        step.set_numerics("off")
        loop.step(x_nd, y_nd)        # (re)warm the uninstrumented bucket
        loop.synchronize()
        t_off = timed()
        step.set_numerics("global")
        loop.step(x_nd, y_nd)        # compile the instrumented bucket
        loop.synchronize()
        t_on = timed()
        last = telemetry.numerics.monitor().last() or {}
        nf = telemetry.value(telemetry.names.ANOMALIES,
                             "nonfinite_grad") or 0
        def sig(v):
            v = float(v)
            return float(f"{v:.6g}") if onp.isfinite(v) else repr(v)

        out = {
            "grad_norm_final": sig(last.get("grad_norm", 0.0)),
            "update_ratio": sig(last.get("update_ratio", 0.0)),
            "nonfinite_events": int(nf),
            "numerics_overhead_pct":
                round((t_on - t_off) / t_off * 100.0, 2)
                if t_off > 0 else None,
        }
        log(f"bench[{tag}]: numerics {out}")
        return out
    except Exception as e:  # pragma: no cover - must not kill the leg
        log(f"bench[{tag}]: numerics probe failed "
            f"({type(e).__name__}: {e})")
        return None
    finally:
        try:
            step.set_numerics(prev_mode)
        except Exception:  # pragma: no cover - defensive
            pass


def run_framework_bench(tag, loop, x, y, warmup, steps):
    """AOT-compile the framework step for this shape bucket, then run
    warmup + the timed loop. The timed loop runs PIPELINED: batches are
    staged onto the device by the background prefetcher
    (gluon/data/prefetcher.py), ``loop.step`` dispatches ahead of the
    device under the bounded in-flight window (MXNET_INFLIGHT_STEPS),
    and NO per-step host read happens — the one host fetch at the end is
    the completion barrier the throughput number needs (block_until_ready
    can return early on tunneled platforms). The loop runs with
    MXNET_TELEMETRY semantics ON, so the leg ships the full telemetry
    story: the engine dict ({input_wait_ms, inflight_window,
    host_sync_count, ...}, now read from the metrics registry instead of
    hand-rolled counters) plus a telemetry dict with the phase-duration
    summary, the MFU gauge (cost_analysis flops / step time / roofline),
    anomaly count, and the full registry snapshot. Returns (dt_seconds,
    flops, final_loss, analysis_dict, engine_dict, telemetry_dict)."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    names = telemetry.names
    x_nd, y_nd = mx.nd.from_jax(x), mx.nd.from_jax(y)
    flops = loop.compiled_step.aot_compile(x_nd, y_nd)
    telemetry.enable(True)
    t0 = time.perf_counter()
    for _ in range(warmup):
        loss = loop.step(x_nd, y_nd)
    loop.synchronize()
    _flush(loss._data)
    fused = loop.compiled_step.mode == "fused"
    log(f"bench[{tag}]: warmup (incl. compile) "
        f"{time.perf_counter() - t0:.1f}s, "
        f"loss={float(loss._data.mean()):.3f}, mode="
        f"{loop.compiled_step.mode}, traces={loop.compiled_step.n_traces}")
    if not fused:  # pragma: no cover - diagnostic
        log(f"bench[{tag}]: WARNING framework step fell back to eager")
    # zero every series so the leg's registry reads ARE the timed loop
    telemetry.reset()
    peak, _ = peak_tflops()
    if flops:
        loop.arm_mfu(x_nd, y_nd,
                     peak_flops=peak * 1e12 if peak else None)
    t0 = time.perf_counter()
    for bx, by in loop.prefetch((x_nd, y_nd) for _ in range(steps)):
        loss = loop.step(bx, by)
    loop.synchronize()
    _flush(loss._data)   # completion barrier: ONE host read per leg
    dt = time.perf_counter() - t0
    es = loop.engine_stats()

    def val(name, label=None, scale=1.0, digits=None):
        v = telemetry.value(name, label)
        if v is None:
            return None
        v = v * scale
        return round(v, digits) if digits is not None else int(v)

    engine = {
        # host syncs the pipeline did NOT design: NDArray-level
        # asnumpy/item/wait_to_read inside the timed loop (target: 0)
        "host_sync_count": val(names.HOST_SYNCS, "wait_to_read"),
        "inflight_window": es.get("inflight_window"),
        # consumer-side wait on input staging (prefetch hides h2d copy)
        "input_wait_ms": val(names.PREFETCH_INPUT_WAIT, scale=1e3,
                             digits=2),
        "window_retires": val(names.HOST_SYNCS, "window_retire"),
        "prefetch_starvation": val(names.PREFETCH_STARVATION),
    }
    phase_summary = {
        phase: {k: round(v, 3) for k, v in s.items()}
        for phase, s in telemetry.timeline().summary().items()}
    wd = telemetry.watchdog()
    # space-domain fingerprint (docs/OBSERVABILITY.md "memory"): the
    # compiled program's static peak, the census's live bytes by pool,
    # and the measured per-replica optimizer-state bytes — a ZeRO leg
    # must show the ~N× `optimizer` drop HERE, in measured bytes (the
    # dryrun zero-sharded leg asserts it; these fields put the same
    # numbers next to every BENCH throughput figure)
    try:
        mem_report = loop.compiled_step.memory_report(x_nd, y_nd)
    except Exception as e:  # pragma: no cover - platform-dependent
        log(f"bench[{tag}]: memory_report unavailable "
            f"({type(e).__name__}: {e})")
        mem_report = None
    memory = {
        "compiled_peak_bytes": mem_report.peak_bytes if mem_report
        else None,
        "compiled": mem_report.to_dict() if mem_report else None,
        "live_bytes_by_pool":
            telemetry.memory.census().live_bytes_by_pool(),
        "optimizer_state_bytes":
            loop.compiled_step.optimizer_state_bytes(),
    }
    telem = {
        "mfu_gauge": telemetry.value(names.MFU),
        "flops_per_step": telemetry.value(names.MODEL_FLOPS_PER_STEP),
        "step_time_ewma_ms": val(names.STEP_TIME_EWMA, scale=1e3,
                                 digits=3),
        "anomalies": len(wd.anomalies()),
        "phase_summary": phase_summary,
        "memory": memory,
        "snapshot": telemetry.snapshot(),
    }
    # numerics-domain fingerprint AFTER the snapshot: the probe runs
    # its own short loops and must not skew the timed-loop series
    telem["numerics"] = numerics_probe(tag, loop, x_nd, y_nd)
    # elastic fingerprint (only when MXNET_ELASTIC is explicitly armed):
    # recoveries the supervisor logged this process + their total
    # downtime — a bench leg that silently recovered mid-timing must
    # say so next to its throughput number
    try:
        from mxnet_tpu import elastic
        if elastic.armed():
            evs = elastic.recovery_log().events()
            telem["elastic"] = {
                "recoveries": len(evs),
                "recovery_downtime_s": round(
                    sum(e["downtime_s"] for e in evs), 3),
            }
    except Exception as e:  # pragma: no cover - defensive
        log(f"bench[{tag}]: elastic stats unavailable "
            f"({type(e).__name__}: {e})")
    log(f"bench[{tag}]: final loss={float(loss._data.mean()):.3f} "
        f"engine={engine} mfu_gauge={telem['mfu_gauge']} "
        f"anomalies={telem['anomalies']} "
        f"peak_bytes={memory['compiled_peak_bytes']} "
        f"pools={memory['live_bytes_by_pool']}")
    analysis = analyze_framework_step(tag, loop, x_nd, y_nd)
    return dt, flops, loss, analysis, engine, telem


def matmul_roofline():
    """Achieved bf16 GEMM TFLOP/s: best over several large matmul shapes.
    8192³ underreports the chip by ~40%; the max lives at big-K
    rectangular shapes where the output write is amortized (r5 measured:
    8192x65536x8192 at 163 TFLOP/s = 83% of v5e peak vs 113 for 8192³).
    Skipped on CPU (meaningless there)."""
    if jax.default_backend() == "cpu":
        return None
    best = None
    for m, k, n in ((8192, 8192, 8192), (12288, 12288, 12288),
                    (8192, 65536, 8192), (16384, 32768, 16384)):
        # ~35 TFLOP of work per shape so each probe times comparably
        iters = max(3, int(round(35e12 / (2 * m * k * n))))
        a = jnp.asarray(onp.random.randn(m, k), jnp.bfloat16)
        b = jnp.asarray(onp.random.randn(k, n), jnp.bfloat16)
        f = jax.jit(lambda a, b: a @ b)
        c = f(a, b)
        _flush(c)
        t0 = time.perf_counter()
        for _ in range(iters):
            c = f(a, b)
        _flush(c)
        dt = time.perf_counter() - t0
        tfs = 2 * m * k * n * iters / dt / 1e12
        log(f"bench: roofline probe {m}x{k}x{n} iters={iters}: "
            f"{tfs:.1f} TFLOP/s")
        best = tfs if best is None or tfs > best else best
        del a, b, c
    return best


def bench_resnet(dtype):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from __graft_entry__ import _init_net

    on_accel = jax.default_backend() != "cpu"
    try:
        bs = int(os.environ.get("MXNET_BENCH_BS") or 128) if on_accel \
            else 4
    except ValueError:
        raise SystemExit("MXNET_BENCH_BS must be an integer, got "
                         f"{os.environ['MXNET_BENCH_BS']!r}")
    if bs <= 0:
        raise SystemExit(f"MXNET_BENCH_BS must be positive, got {bs}")
    size = 224 if on_accel else 32
    warmup = 3 if on_accel else 1
    steps = 20 if on_accel else 2

    onp.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    # eager init runs BEFORE amp.init(): the fp32 eager path is
    # compile-cached across runs, while flowing-bf16 eager would trigger
    # ~100 fresh remote compiles on tunneled platforms
    _init_net(net, (1, 3, size, size))
    if dtype == "bf16":
        mx.amp.init()
    try:
        loop = framework_loop(net, lr=0.1)
        x = jnp.asarray(onp.random.uniform(size=(bs, 3, size, size))
                        .astype("float32"))
        y = jnp.asarray(onp.random.randint(0, 1000, size=(bs,))
                        .astype("int32"))
        dt, flops, _, ana, eng, tel = run_framework_bench(
            "resnet", loop, x, y, warmup, steps)
    finally:
        if dtype == "bf16":
            mx.amp.uninit()
    img_s = bs * steps / dt
    tfs = flops * steps / dt / 1e12 if flops and on_accel else None
    return {"img_s": img_s, "tflops": tfs, "bs": bs, "analysis": ana,
            "engine": eng, "telemetry": tel}


def bench_bert(dtype):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import bert

    on_accel = jax.default_backend() != "cpu"
    bs, seqlen = (32, 512) if on_accel else (2, 32)
    warmup, steps = (3, 10) if on_accel else (1, 2)
    log(f"bench[bert]: bs={bs} seq={seqlen}")

    onp.random.seed(0)
    net = bert.BERTClassifier(
        bert.bert_base(max_length=seqlen) if on_accel
        else bert.bert_small_test(), num_classes=2)
    vocab = 1000 if on_accel else 128  # stay inside the model's vocab
    tokens = onp.random.randint(0, vocab, size=(1, seqlen)).astype("int32")
    net.initialize()
    net(mx.nd.array(tokens))  # eager init pre-AMP (see bench_resnet note)
    if dtype == "bf16":
        mx.amp.init()
    try:
        # lr small enough that random-label steps stay finite on every
        # config (throughput is lr-independent)
        loop = framework_loop(net, lr=1e-3)
        x = jnp.asarray(onp.random.randint(0, vocab, size=(bs, seqlen))
                        .astype("int32"))
        y = jnp.asarray(onp.random.randint(0, 2, size=(bs,)).astype("int32"))
        dt, flops, _, ana, eng, tel = run_framework_bench(
            "bert", loop, x, y, warmup, steps)
    finally:
        if dtype == "bf16":
            mx.amp.uninit()
    tok_s = bs * seqlen * steps / dt
    tfs = flops * steps / dt / 1e12 if flops and on_accel else None
    return {"tok_s": tok_s, "tflops": tfs, "analysis": ana,
            "engine": eng, "telemetry": tel}


def bench_lstm(dtype):
    """LSTM LM training throughput (BASELINE.md row 4: reference
    example/rnn word_lm on the cuDNN RNN path; here gluon.rnn.LSTM
    lowers to one lax.scan). Medium config: vocab 33278 (wikitext-2),
    650-d embed/hidden, 2 layers, bs=64, bptt=35."""
    import importlib.util
    import mxnet_tpu as mx

    spec = importlib.util.spec_from_file_location(
        "train_lstm_lm",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "examples", "train_lstm_lm.py"))
    ex = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ex)

    on_accel = jax.default_backend() != "cpu"
    vocab, embed, hidden, layers = (33278, 650, 650, 2) if on_accel \
        else (128, 16, 32, 1)
    bs, seq = (64, 35) if on_accel else (4, 8)
    warmup, steps = (3, 20) if on_accel else (1, 2)
    log(f"bench[lstm]: vocab={vocab} hidden={hidden} bs={bs} bptt={seq}")

    onp.random.seed(0)
    net = ex.WordLM(vocab, embed, hidden, layers)
    net.initialize()
    tokens = onp.random.randint(0, vocab, size=(1, seq)).astype("int32")
    net(mx.nd.array(tokens))  # eager init pre-AMP (see bench_resnet note)
    if dtype == "bf16":
        mx.amp.init()
    try:
        loop = framework_loop(net, lr=0.5)
        x = jnp.asarray(onp.random.randint(
            0, vocab, size=(bs, seq)).astype("int32"))
        y = jnp.asarray(onp.random.randint(
            0, vocab, size=(bs, seq)).astype("int32"))
        dt, flops, _, ana, eng, tel = run_framework_bench(
            "lstm", loop, x, y, warmup, steps)
    finally:
        if dtype == "bf16":
            mx.amp.uninit()
    tok_s = bs * seq * steps / dt
    tfs = flops * steps / dt / 1e12 if flops and on_accel else None
    return {"tok_s": tok_s, "tflops": tfs, "analysis": ana,
            "engine": eng, "telemetry": tel}


class _SSDResNet50:
    """Builder for the SSD-ResNet50 bench model (BASELINE.md row 5):
    resnet50_v1 features (minus global pool) + two extra downsample
    scales, 3x3 cls/loc heads per scale, anchors via MultiBoxPrior —
    the reference example/ssd architecture re-expressed in this Gluon."""

    @staticmethod
    def build(num_classes=20):
        from mxnet_tpu import gluon, nd
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.model_zoo import vision

        SIZES = [(0.2, 0.272), (0.37, 0.447), (0.54, 0.619)]
        RATIOS = (1.0, 2.0, 0.5)
        A = len(SIZES[0]) + len(RATIOS) - 1

        class SSD(gluon.Block):
            def __init__(self):
                super().__init__()
                base = vision.resnet50_v1()
                self.backbone = nn.Sequential()
                feats = list(base.features._children.values())[:-1]
                for blk in feats:
                    self.backbone.add(blk)
                self.extra1 = nn.Sequential()
                self.extra1.add(nn.Conv2D(512, 3, strides=2, padding=1,
                                          activation="relu"))
                self.extra2 = nn.Sequential()
                self.extra2.add(nn.Conv2D(256, 3, strides=2, padding=1,
                                          activation="relu"))
                self.cls_heads = []
                self.loc_heads = []
                for i in range(3):
                    ch = nn.Conv2D(A * (num_classes + 1), 3, padding=1)
                    lh = nn.Conv2D(A * 4, 3, padding=1)
                    setattr(self, f"cls{i}", ch)
                    setattr(self, f"loc{i}", lh)
                    self.cls_heads.append(ch)
                    self.loc_heads.append(lh)
                self._nc = num_classes

            def forward(self, x):
                feats = [self.backbone(x)]
                feats.append(self.extra1(feats[-1]))
                feats.append(self.extra2(feats[-1]))
                anchors, clses, locs = [], [], []
                for i, f in enumerate(feats):
                    anchors.append(nd.contrib.MultiBoxPrior(
                        f, sizes=SIZES[i], ratios=RATIOS))
                    c = self.cls_heads[i](f)
                    b, _, h, w = c.shape
                    clses.append(c.transpose((0, 2, 3, 1)).reshape(
                        (b, h * w * A, self._nc + 1)))
                    locs.append(self.loc_heads[i](f).transpose(
                        (0, 2, 3, 1)).reshape((b, -1)))
                return (nd.concat(*anchors, dim=1),
                        nd.concat(*clses, dim=1),
                        nd.concat(*locs, dim=1))

        return SSD()


def bench_ssd(dtype):
    """SSD-ResNet50 training throughput, MultiBoxTarget matching inside
    the compiled step and one on-device-NMS eval (MultiBoxDetection)."""
    import mxnet_tpu as mx
    from mxnet_tpu import _tape, nd
    from mxnet_tpu.ndarray.ndarray import NDArray
    from __graft_entry__ import _functional_apply

    on_accel = jax.default_backend() != "cpu"
    bs, size = (32, 300) if on_accel else (2, 64)
    warmup, steps = (3, 10) if on_accel else (1, 2)
    log(f"bench[ssd]: bs={bs} size={size}")

    onp.random.seed(0)
    net = _SSDResNet50.build()
    net.initialize()
    net(mx.nd.array(onp.random.uniform(
        size=(1, 3, size, size)).astype("float32")))  # eager init pre-AMP
    if dtype == "bf16":
        mx.amp.init()
    try:
        params = [p for p in net.collect_params().values()
                  if p._data is not None]
        trainable = tuple(p.grad_req != "null" for p in params)
        apply_fn = _functional_apply(net, params, train=True,
                                     with_state=True)
        lr, momentum = 1e-3, 0.9

        def loss_fn(pd, x, labels):
            (anchors, cls, loc), state = apply_fn(pd, x,
                                                  jax.random.PRNGKey(0))
            prev = _tape.set_recording(False)
            try:
                loc_t, loc_mask, cls_t = nd.contrib.MultiBoxTarget(
                    NDArray(jax.lax.stop_gradient(anchors)),
                    NDArray(labels),
                    NDArray(jax.lax.stop_gradient(cls)
                            .transpose((0, 2, 1))))
                ce = nd.softmax_cross_entropy(
                    NDArray(cls.reshape((-1, cls.shape[-1]))),
                    NDArray(cls_t._data.reshape((-1,))))
                l1 = nd.abs(NDArray(loc) * loc_mask - loc_t * loc_mask)
            finally:
                _tape.set_recording(prev)
            l = ce._data / cls.shape[0] / cls.shape[1] \
                + jnp.mean(l1._data)
            return l, state

        def train_step(pd, mom, x, labels):
            (loss, state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(pd, x, labels)
            new_mom = tuple(momentum * m + g for m, g in zip(mom, grads))
            new_pd = tuple(d - lr * m if t else s
                           for d, m, s, t in zip(pd, new_mom, state,
                                                 trainable))
            return new_pd, new_mom, loss

        pd = tuple(jnp.array(p._data._data, copy=True) for p in params)
        mom = tuple(jnp.zeros_like(d) for d in pd)
        x = jnp.asarray(onp.random.uniform(
            size=(bs, 3, size, size)).astype("float32"))
        # one random ground-truth box per image: (B, 1, 5) [cls x0 y0 x1 y1]
        lab = onp.zeros((bs, 1, 5), "float32")
        lab[:, 0, 0] = onp.random.randint(0, 20, size=bs)
        x0 = onp.random.uniform(0, 0.6, size=(bs, 2)).astype("float32")
        lab[:, 0, 1:3] = x0
        lab[:, 0, 3:5] = x0 + 0.3
        labels = jnp.asarray(lab)

        step, flops = compile_step(train_step, pd, mom, x, labels)
        t0 = time.perf_counter()
        for _ in range(warmup):
            pd, mom, loss = step(pd, mom, x, labels)
        _flush(loss)
        log(f"bench[ssd]: warmup {time.perf_counter() - t0:.1f}s, "
            f"loss={float(loss):.3f}")
        t0 = time.perf_counter()
        for _ in range(steps):
            pd, mom, loss = step(pd, mom, x, labels)
        _flush(loss)
        dt = time.perf_counter() - t0

        # on-device NMS eval pass (the reference's custom CUDA NMS; here
        # MultiBoxDetection's lax loop) — ONE jitted program: eager
        # per-op dispatch through the tunnel would cost minutes
        eval_apply = _functional_apply(net, params, train=False)

        def eval_prog(pd, xe):
            anchors, cls, loc = eval_apply(pd, xe, jax.random.PRNGKey(0))
            prev = _tape.set_recording(False)
            try:
                probs = nd.softmax(NDArray(cls).transpose((0, 2, 1)),
                                   axis=1)
                det = nd.contrib.MultiBoxDetection(
                    probs, NDArray(loc), NDArray(anchors),
                    nms_threshold=0.45, threshold=0.01)
            finally:
                _tape.set_recording(prev)
            return det._data

        xe = jnp.asarray(onp.random.uniform(
            size=(4, 3, size, size)).astype("float32"))
        t0 = time.perf_counter()
        det = jax.jit(eval_prog)(pd, xe)
        onp.asarray(det)
        t_compile = time.perf_counter() - t0
        t0 = time.perf_counter()
        onp.asarray(jax.jit(eval_prog)(pd, xe))
        nms_s = time.perf_counter() - t0
        log(f"bench[ssd]: on-device NMS eval (bs=4): {nms_s*1e3:.0f} ms "
            f"(+{t_compile:.1f}s compile)")
    finally:
        if dtype == "bf16":
            mx.amp.uninit()
    img_s = bs * steps / dt
    tfs = flops * steps / dt / 1e12 if flops and on_accel else None
    return {"img_s": img_s, "tflops": tfs}


def bench_serving(dtype):
    """Inference serving leg (mx.serving, docs/SERVING.md): a 3-layer
    MLP served through the AOT-compiled predictor, measured three ways —

    - closed-loop UNBATCHED baseline: 8 concurrent clients, requests
      served ONE AT A TIME (the device is an exclusive resource — one
      program executes at a time; a lock models that on the CPU
      backend, where concurrent XLA calls would otherwise borrow host
      parallelism no accelerator offers) — the pre-serving-engine
      posture;
    - closed-loop through the DynamicBatcher: same 8 clients, requests
      coalesced into shape buckets and pipelined through the dispatch
      window — the acceptance bar is batched QPS > unbatched QPS;
    - open-loop Poisson arrivals at ~30% of the batched closed-loop
      capacity: the honest latency distribution without coordinated
      omission (closed loops self-throttle and hide queueing).

    Reports p50/p99 latency, QPS, batch-fill ratio, and the persistent
    compile-cache hit rate next to the training legs, plus an INT8
    variant probe through the post-training-quantization path."""
    import mxnet_tpu as mx
    from mxnet_tpu import serving, telemetry
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.runtime import compile_cache_stats
    from mxnet_tpu.serving import loadgen

    on_accel = jax.default_backend() != "cpu"
    in_dim, hidden, classes = (1024, 4096, 1000) if on_accel \
        else (256, 1024, 64)
    requests = 512 if on_accel else 256
    conc = 8
    buckets = (1, 2, 4, 8, 16, 32)
    log(f"bench[serving]: mlp {in_dim}->{hidden}x2->{classes} "
        f"concurrency={conc} requests={requests} buckets={buckets}")

    onp.random.seed(0)

    def build_net():
        net = nn.HybridSequential()
        net.add(nn.Dense(hidden, activation="relu", in_units=in_dim),
                nn.Dense(hidden, activation="relu", in_units=hidden),
                nn.Dense(classes, in_units=hidden))
        net.initialize()
        net(mx.nd.array(onp.zeros((1, in_dim), "float32")))
        return net

    serve_dtype = "bfloat16" if dtype == "bf16" and on_accel \
        else "float32"
    pred = serving.predictor_for(build_net(), dtype=serve_dtype,
                                 bucket_sizes=buckets)
    telemetry.enable(True)
    x1 = mx.nd.array(onp.random.randn(1, in_dim).astype("float32"))
    t0 = time.perf_counter()
    pred.warmup(x1)
    t_warm = time.perf_counter() - t0
    log(f"bench[serving]: warmup (AOT all buckets) {t_warm:.1f}s, "
        f"programs={pred.n_traces}")
    telemetry.reset()

    X = onp.random.randn(requests, in_dim).astype("float32")

    # one-request-at-a-time: the device executes one program at a time
    # (a lock models the exclusive accelerator on the CPU backend)
    import threading
    device_lock = threading.Lock()

    def issue_unbatched(i):
        with device_lock:
            out = pred.predict(
                mx.nd.array(X[i % requests:i % requests + 1]))
            jax.block_until_ready(out._data)

    unbatched = loadgen.run_closed_loop(issue_unbatched, conc, requests)
    log(f"bench[serving]: unbatched {unbatched}")

    batcher = serving.DynamicBatcher(pred, max_batch=buckets[-1],
                                     timeout_ms=2.0)
    batched = loadgen.run_closed_loop(
        lambda i: batcher.submit(
            mx.nd.array(X[i % requests:i % requests + 1])).result(120),
        conc, requests)
    fill = batcher.batch_fill
    bstats = dict(batcher.stats)
    batcher.close()
    log(f"bench[serving]: batched {batched} fill={fill} {bstats}")

    open_rep = None
    if batched.get("qps"):
        batcher2 = serving.DynamicBatcher(pred, max_batch=buckets[-1],
                                          timeout_ms=2.0)
        open_rep = loadgen.run_open_loop(
            lambda i: batcher2.submit(
                mx.nd.array(X[i % requests:i % requests + 1])).result,
            rate_qps=0.3 * batched["qps"],
            requests=max(64, requests // 2))
        batcher2.close()
        log(f"bench[serving]: open-loop {open_rep}")

    # INT8 serving variant through the post-training-quantization path
    int8_probe = None
    try:
        calib = [mx.nd.array(X[i:i + 8]) for i in range(0, 32, 8)]
        pred8 = serving.predictor_for(build_net(), dtype="int8",
                                      calib_data=calib,
                                      bucket_sizes=buckets)
        pred8.warmup(x1, buckets=(1, buckets[-1]))
        b8 = serving.DynamicBatcher(pred8, max_batch=buckets[-1],
                                    timeout_ms=2.0)
        int8_probe = loadgen.run_closed_loop(
            lambda i: b8.submit(
                mx.nd.array(X[i % requests:i % requests + 1])).result(120),
            conc, max(64, requests // 4))
        b8.close()
        log(f"bench[serving]: int8 {int8_probe}")
    except Exception as e:  # pragma: no cover - variant must not kill leg
        log(f"bench[serving]: int8 probe failed ({type(e).__name__}: {e})")

    # resilience probes (docs/SERVING.md "Resilient serving"):
    # (a) overload A/B — open-loop Poisson at ~2x the measured batched
    # capacity with a per-request deadline. The unshedded baseline
    # accepts everything and its p99 blows past the deadline as the
    # queue grows; MXNET_SERVING_SHED=deadline rejects at admission
    # (typed Overloaded) so the ACCEPTED requests keep their p99.
    # Both runs land in the BENCH json.
    overload = None
    saved_shed = os.environ.get("MXNET_SERVING_SHED")
    try:
        if batched.get("qps"):
            rate = 2.0 * batched["qps"]
            deadline_ms = max(25.0, 4.0 * (batched.get("p50_ms") or 5.0))
            n_over = max(96, requests // 4)
            overload = {"rate_qps": round(rate, 1),
                        "deadline_ms": round(deadline_ms, 1)}
            # baseline: no shedding, no deadline — the honest p99 of
            # an overloaded FIFO queue
            os.environ["MXNET_SERVING_SHED"] = "off"
            b_off = serving.DynamicBatcher(pred, max_batch=buckets[-1],
                                           timeout_ms=2.0)
            rep_off = loadgen.run_open_loop(
                lambda i: b_off.submit(
                    mx.nd.array(X[i % requests:i % requests + 1]),
                    deadline_ms=0).result,
                rate_qps=rate, requests=n_over)
            b_off.close()
            overload["shed_off"] = {
                k: rep_off.get(k) for k in
                ("qps", "goodput_qps", "p50_ms", "p99_ms",
                 "reject_rate", "deadline_miss_rate", "outcomes")}
            miss_base = (rep_off.get("p99_ms") or 0) > deadline_ms
            # shed=deadline: same traffic, per-request deadline armed
            os.environ["MXNET_SERVING_SHED"] = "deadline"
            b_on = serving.DynamicBatcher(pred, max_batch=buckets[-1],
                                          timeout_ms=2.0)
            rep_on = loadgen.run_open_loop(
                lambda i: b_on.submit(
                    mx.nd.array(X[i % requests:i % requests + 1]),
                    deadline_ms=deadline_ms).result,
                rate_qps=rate, requests=n_over,
                deadline_s=deadline_ms / 1e3)
            b_on.close()
            overload["shed_deadline"] = {
                k: rep_on.get(k) for k in
                ("qps", "goodput_qps", "p50_ms", "p99_ms",
                 "reject_rate", "deadline_miss_rate", "outcomes")}
            overload["baseline_missed_deadline"] = bool(miss_base)
            overload["shed_kept_p99_in_deadline"] = bool(
                (rep_on.get("p99_ms") or 1e9) <= deadline_ms)
            log(f"bench[serving]: overload A/B @ {rate:.0f} req/s "
                f"deadline={deadline_ms:.0f}ms — off p99="
                f"{rep_off.get('p99_ms')}ms goodput="
                f"{rep_off.get('goodput_qps')} | deadline p99="
                f"{rep_on.get('p99_ms')}ms goodput="
                f"{rep_on.get('goodput_qps')} reject_rate="
                f"{rep_on.get('reject_rate')}")
    except Exception as e:  # pragma: no cover - probe must not kill leg
        log(f"bench[serving]: overload probe failed "
            f"({type(e).__name__}: {e})")
    finally:
        if saved_shed is None:
            os.environ.pop("MXNET_SERVING_SHED", None)
        else:
            os.environ["MXNET_SERVING_SHED"] = saved_shed

    # (b) device-loss recovery — a small supervised burst with one
    # injected revocation: {recoveries, recovery_downtime_s} prove the
    # ServingSupervisor's rebuild path end to end (a dedicated probe
    # net keeps the rebuild cheap; the machinery, not the model, is
    # under test)
    resilience = None
    try:
        from mxnet_tpu.testing import faults

        def build_probe():
            mx.random.seed(11)
            pnet = nn.HybridSequential()
            pnet.add(nn.Dense(64, activation="relu", in_units=32),
                     nn.Dense(8, in_units=64))
            pnet.initialize()
            pnet(mx.nd.array(onp.zeros((1, 32), "float32")))
            return serving.CompiledPredictor(pnet,
                                             bucket_sizes=(1, 2, 4))

        xp = mx.nd.array(onp.zeros((1, 32), "float32"))
        Xp = onp.random.randn(32, 32).astype("float32")
        sup = serving.ServingSupervisor(build_probe, example=(xp,),
                                        max_batch=4, timeout_ms=2.0)
        faults.configure("serving.dispatch:before=2:revoke:1")
        try:
            rep_r = loadgen.run_closed_loop(
                lambda i: sup.submit(
                    mx.nd.array(Xp[i % 32:i % 32 + 1])).result(60),
                concurrency=4, requests=48)
        finally:
            faults.reset()
            sup.close()
        resilience = {
            "recoveries": sup.stats["recoveries"],
            "recovery_downtime_s": round(
                sup.stats["recovery_downtime_s"], 3),
            "requeued": sup.stats["requeued"],
            "breaker": [s for s, _t, _c in sup.breaker.transitions],
            "outcomes": rep_r.get("outcomes"),
        }
        log(f"bench[serving]: recovery probe {resilience}")
    except Exception as e:  # pragma: no cover - probe must not kill leg
        log(f"bench[serving]: recovery probe failed "
            f"({type(e).__name__}: {e})")

    cc = compile_cache_stats()
    cache = {"enabled": cc["enabled"], "hits": cc["hits"],
             "misses": cc["misses"],
             "hit_rate": round(cc["hits"] / (cc["hits"] + cc["misses"]), 3)
             if (cc["hits"] + cc["misses"]) else None}
    speedup = round(batched["qps"] / unbatched["qps"], 2) \
        if batched.get("qps") and unbatched.get("qps") else None
    log(f"bench[serving]: batched-vs-unbatched QPS speedup {speedup}x "
        f"cache={cache}")
    return {
        "qps": batched.get("qps"),
        "p50_ms": batched.get("p50_ms"),
        "p99_ms": batched.get("p99_ms"),
        "concurrency": conc,
        "batch_fill": round(fill, 3) if fill is not None else None,
        "unbatched_qps": unbatched.get("qps"),
        "unbatched_p50_ms": unbatched.get("p50_ms"),
        "speedup_vs_unbatched": speedup,
        "open_loop": open_rep,
        "int8": int8_probe,
        # resilience posture (docs/SERVING.md "Resilient serving")
        "goodput_qps": batched.get("goodput_qps"),
        "reject_rate": batched.get("reject_rate"),
        "deadline_miss_rate": batched.get("deadline_miss_rate"),
        "overload": overload,
        "resilience": resilience,
        "recoveries": resilience["recoveries"]
        if resilience is not None else None,
        "recovery_downtime_s": resilience["recovery_downtime_s"]
        if resilience is not None else None,
        "compile_cache": cache,
        "warmup_s": round(t_warm, 2),
        "programs": pred.n_traces,
        "dtype": serve_dtype,
        "batcher": {k: bstats.get(k) for k in
                    ("requests", "batches", "rows", "padded_rows",
                     "flush_full", "flush_timeout", "flush_idle",
                     "errors")},
        # serving-scope autotune posture (tuned batcher knobs replayed
        # from MXNET_AUTOTUNE_CACHE, or the defaults on a miss)
        **(pred.autotune_result.bench_dict()
           if getattr(pred, "autotune_result", None) is not None else
           {"autotune_config": None, "autotune_trials": None,
            "autotune_delta_pct": None}),
    }


def bench_decode(dtype):
    """Continuous-batching decode leg (mx.serving.decode,
    docs/SERVING.md "Continuous batching"): the reference decoder
    served over a heavy-tailed request mix (mostly short decodes, a
    few long ones — the shape that makes whole-batch scheduling bleed)
    two ways with IDENTICAL compiled programs:

    - **static**: the classic whole-batch baseline — fill every slot,
      prefill all prompts, decode until the LAST member finishes;
    - **continuous**: iteration-level scheduling — finished slots
      refilled between steps, chunked prefill interleaved with decode.

    The acceptance bar is continuous token throughput >= 2x static at
    this mix, with lower short-request TTFT. Reports
    decode_tokens_per_sec, exact TTFT/TPOT percentiles, KV page
    utilization, and the kernel dispatch posture."""
    from mxnet_tpu import serving
    from mxnet_tpu.ops import kernels as _kern

    on_accel = jax.default_backend() != "cpu"
    vocab, d_model, heads = (256, 128, 4) if on_accel else (64, 32, 2)
    n_req = 32 if on_accel else 16
    ladder = (1, 2, 4, 8) if on_accel else (1, 2, 4)
    page_size = 16 if on_accel else 8
    rng = onp.random.RandomState(7)
    model = serving.TinyDecoder(vocab=vocab, d_model=d_model,
                                num_heads=heads, seed=0)
    prompts, mns = [], []
    for i in range(n_req):
        prompts.append(rng.randint(0, vocab,
                                   size=int(rng.randint(2, 12))))
        mns.append(48 if i % 8 == 0 else int(rng.randint(2, 6)))
    log(f"bench[decode]: {n_req} requests, ladder={ladder}, "
        f"page_size={page_size}, mix=heavy-tail "
        f"(len {min(mns)}..{max(mns)})")
    cont = serving.run_decode(model, prompts, mns, ladder=ladder,
                              page_size=page_size)
    stat = serving.run_decode(model, prompts, mns, ladder=ladder,
                              page_size=page_size, static=True)
    speedup = round(cont["decode_tokens_per_sec"]
                    / stat["decode_tokens_per_sec"], 2) \
        if cont.get("decode_tokens_per_sec") and \
        stat.get("decode_tokens_per_sec") else None
    log(f"bench[decode]: continuous {cont['decode_tokens_per_sec']} "
        f"tok/s (ttft p99 {cont['ttft_p99_ms']}ms) vs static "
        f"{stat['decode_tokens_per_sec']} tok/s (ttft p99 "
        f"{stat['ttft_p99_ms']}ms) — speedup {speedup}x")
    # --- speculative decode + prefix sharing A/B (docs/SERVING.md
    # "Speculative decode & prefix sharing"): a repeated-suffix mix
    # (prompt-lookup drafting territory) whose prompts extend one
    # shared base prefix, decoded plain-greedy vs draft->verify with
    # the prefix cache on. Emitted tokens are bit-identical by
    # contract; the delta is steps, not tokens.
    base = rng.randint(0, vocab, size=3 * page_size).astype(onp.int32)
    sp_prompts, sp_mns = [], []
    for i in range(max(8, n_req // 2)):
        tail = rng.randint(0, vocab, size=2 + (i % 3)).astype(onp.int32)
        sp_prompts.append(onp.concatenate([base, tail]))
        sp_mns.append(24)
    plain = serving.run_decode(model, sp_prompts, sp_mns,
                               ladder=ladder, page_size=page_size,
                               spec_k=0, prefix_share=False)
    spec = serving.run_decode(model, sp_prompts, sp_mns,
                              ladder=ladder, page_size=page_size,
                              spec_k=4, prefix_share=True)
    speedup_spec = round(spec["decode_tokens_per_sec"]
                         / plain["decode_tokens_per_sec"], 2) \
        if spec.get("decode_tokens_per_sec") and \
        plain.get("decode_tokens_per_sec") else None
    tps = (spec.get("tokens_per_step") or {}).get("mean")
    cap = max(1, spec.get("kv_num_pages", 2) - 1)
    shared_pct = round(100.0 * spec.get("kv_shared_peak", 0) / cap, 2)
    log(f"bench[decode]: speculative {spec['decode_tokens_per_sec']} "
        f"tok/s vs greedy {plain['decode_tokens_per_sec']} tok/s — "
        f"speedup {speedup_spec}x, acceptance "
        f"{spec.get('acceptance_rate')}, tokens/step {tps}, shared "
        f"pages peak {shared_pct}% of pool")

    # --- GQA transformer workload: the second decode model over the
    # same engine/cache (half the K/V heads -> half the cache bytes
    # per token at this query width)
    from mxnet_tpu.gluon import GQADecoder
    gqa = GQADecoder(vocab=vocab, d_model=d_model, num_heads=heads * 2,
                     num_kv_heads=heads, num_layers=2, seed=0)
    gqa_res = serving.run_decode(gqa, prompts[:8], mns[:8],
                                 ladder=ladder, page_size=page_size)
    log(f"bench[decode]: gqa transformer "
        f"{gqa_res['decode_tokens_per_sec']} tok/s "
        f"({gqa.num_heads} q heads / {gqa.num_kv_heads} kv heads)")
    return {
        "decode_tokens_per_sec": cont.get("decode_tokens_per_sec"),
        "ttft_p50_ms": cont.get("ttft_p50_ms"),
        "ttft_p99_ms": cont.get("ttft_p99_ms"),
        "tpot_p50_ms": cont.get("tpot_p50_ms"),
        "tpot_p99_ms": cont.get("tpot_p99_ms"),
        "kv_page_util": cont.get("kv_page_util"),
        "speedup_vs_static": speedup,
        "static_tokens_per_sec": stat.get("decode_tokens_per_sec"),
        "static_ttft_p99_ms": stat.get("ttft_p99_ms"),
        "tokens": cont.get("tokens"),
        "requests": n_req,
        "steps": cont.get("steps"),
        "static_steps": stat.get("steps"),
        "prefill_chunks": cont.get("prefill_chunks"),
        "slot_ladder": list(ladder),
        "page_size": page_size,
        "kernel_path": _kern.dispatch_table().get("rnn_decode_step"),
        "spec_acceptance_rate": spec.get("acceptance_rate"),
        "tokens_per_step": tps,
        "kv_shared_page_pct": shared_pct,
        "speedup_vs_nonspec": speedup_spec,
        "spec_detail": spec,
        "gqa_tokens_per_sec": gqa_res.get("decode_tokens_per_sec"),
        "gqa_detail": gqa_res,
        "continuous_detail": cont,
        "static_detail": stat,
    }


def bench_fleet(dtype):
    """Serving fleet leg (mx.serving.fleet, docs/SERVING.md "Serving
    fleet"): a small probe MLP served by a FleetController, measured
    four ways —

    - closed-loop goodput through ONE replica (the single-replica
      posture PR 15 ends at);
    - the same traffic through a 3-replica fleet behind the
      least-wait router (``fleet_speedup_vs_single``);
    - kill-one-mid-burst: a targeted device revocation at one
      replica's dispatch seam while the burst runs — goodput under
      failover, plus the replica's out-of-rotation window
      (``kill_recovery_downtime_s``: replica_lost -> restart, from
      the structured FleetEvent log);
    - a rolling weight swap under the same fleet
      (``swap_downtime_s``: the LONGEST single replica's
      drain->serving window; the fleet itself never goes dark).

    The probe model is deliberately tiny — the routing/failover/
    rollout machinery, not the matmuls, is under test."""
    import tempfile
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.checkpoint import atomic as ck_atomic
    from mxnet_tpu.checkpoint import state as ck_state
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.serving import loadgen
    from mxnet_tpu.testing import faults

    in_dim, hidden, classes = 32, 64, 8
    requests, conc, buckets = 96, 6, (1, 2, 4)
    n_dev = len(dist.available_devices())
    n_fleet = min(3, n_dev)

    def build_probe():
        mx.random.seed(17)
        net = nn.HybridSequential()
        net.add(nn.Dense(hidden, activation="relu", in_units=in_dim),
                nn.Dense(classes, in_units=hidden))
        net.initialize()
        net(mx.nd.array(onp.zeros((1, in_dim), "float32")))
        return net

    def build():
        return serving.CompiledPredictor(build_probe(),
                                         bucket_sizes=buckets)

    xp = mx.nd.array(onp.zeros((1, in_dim), "float32"))
    Xp = onp.random.RandomState(3).randn(64, in_dim).astype("float32")

    def make_args(i):
        return (mx.nd.array(Xp[i % 64:i % 64 + 1]),)

    log(f"bench[fleet]: probe mlp {in_dim}->{hidden}->{classes}, "
        f"{n_fleet} replicas over {n_dev} device(s), "
        f"requests={requests} concurrency={conc}")

    single = serving.FleetController(build, example=(xp,), replicas=1,
                                     max_batch=buckets[-1],
                                     timeout_ms=2.0)
    rep1 = loadgen.run_closed_loop(
        loadgen.fleet_issue(single.router, make_args, timeout=60),
        conc, requests)
    single.close()
    log(f"bench[fleet]: 1 replica {rep1}")

    fleet = serving.FleetController(build, example=(xp,),
                                    replicas=n_fleet,
                                    max_batch=buckets[-1],
                                    timeout_ms=2.0)
    repN = loadgen.run_closed_loop(
        loadgen.fleet_issue(fleet.router, make_args, timeout=60),
        conc, requests)
    log(f"bench[fleet]: {n_fleet} replicas {repN}")

    # kill-one-mid-burst A/B: revoke the last replica's device at its
    # dispatch seam while the burst runs; the router + failover keep
    # accepted traffic flowing on the survivors
    kill_rep, kill_downtime = None, None
    if n_fleet >= 2:
        victim = fleet.replicas[-1]
        faults.configure(f"serving.dispatch@{victim.name}:before=1:"
                         f"revoke:d{victim.device.id}")
        try:
            kill_rep = loadgen.run_closed_loop(
                loadgen.fleet_issue(fleet.router, make_args,
                                    timeout=60), conc, requests)
        finally:
            faults.reset()
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline and not any(
                e.kind in ("restart", "restart_failed")
                for e in fleet.events):
            time.sleep(0.05)
        t_lost = next((e.t for e in fleet.events
                       if e.kind == "replica_lost"), None)
        t_back = next((e.t for e in fleet.events
                       if e.kind == "restart"), None)
        if t_lost is not None and t_back is not None:
            kill_downtime = round(max(0.0, t_back - t_lost), 3)
        log(f"bench[fleet]: kill-mid-burst {kill_rep} "
            f"recovery_downtime={kill_downtime}s "
            f"restarts={fleet.stats['restarts']}")

    # rolling weight swap: drain one replica at a time onto a fresh
    # CRC-verified checkpoint; the out-of-rotation window per replica
    # is the honest "downtime" (the fleet keeps serving throughout)
    swap_downtime, swap_total = None, None
    try:
        st = ck_state.capture_train_state(net=build_probe(), step=1)
        root = tempfile.mkdtemp(prefix="mx-fleet-swap-")
        ck_atomic.write_checkpoint(root, 1, st.arrays,
                                   array_meta=st.array_meta,
                                   meta=st.meta)
        t0 = time.perf_counter()
        fleet.swap_weights(root)
        swap_total = round(time.perf_counter() - t0, 3)
        drains = {e.replica: e.t for e in fleet.events
                  if e.kind == "swap_drain"}
        gaps = [e.t - drains[e.replica] for e in fleet.events
                if e.kind == "swap_done" and e.replica in drains]
        swap_downtime = round(max(gaps), 3) if gaps else None
        log(f"bench[fleet]: rolling swap total={swap_total}s "
            f"max_replica_window={swap_downtime}s")
    except Exception as e:  # pragma: no cover - probe must not kill leg
        log(f"bench[fleet]: swap probe failed "
            f"({type(e).__name__}: {e})")
    fstats = dict(fleet.stats)
    fleet.close()

    speedup = round(repN["goodput_qps"] / rep1["goodput_qps"], 2) \
        if repN.get("goodput_qps") and rep1.get("goodput_qps") else None
    log(f"bench[fleet]: fleet-vs-single goodput speedup {speedup}x")
    return {
        "fleet_goodput_qps": repN.get("goodput_qps"),
        "single_goodput_qps": rep1.get("goodput_qps"),
        "fleet_speedup_vs_single": speedup,
        "kill_recovery_downtime_s": kill_downtime,
        "swap_downtime_s": swap_downtime,
        "swap_total_s": swap_total,
        "replicas": n_fleet,
        "fleet_p50_ms": repN.get("p50_ms"),
        "fleet_p99_ms": repN.get("p99_ms"),
        "per_replica": repN.get("replicas"),
        "kill_outcomes": kill_rep.get("outcomes")
        if kill_rep is not None else None,
        "kill_goodput_qps": kill_rep.get("goodput_qps")
        if kill_rep is not None else None,
        "restarts": fstats.get("restarts"),
        "failovers": fstats.get("failovers"),
        "requeued": fstats.get("requeued"),
        "swaps": fstats.get("swaps"),
    }


def main():
    model = os.environ.get("MXNET_BENCH_MODEL", "all")
    dtype = os.environ.get("MXNET_BENCH_DTYPE", "bf16")
    if dtype not in ("bf16", "fp32"):
        raise SystemExit(f"MXNET_BENCH_DTYPE must be bf16|fp32, got {dtype}")
    # every leg runs under the autotune REPLAY gate: a tuned config
    # persisted by an offline MXNET_AUTOTUNE=on pass is applied with
    # zero trials, a miss runs the shipped defaults — the leg's
    # {autotune_config, autotune_trials, autotune_delta_pct} fields
    # record which happened (an explicit MXNET_AUTOTUNE wins)
    os.environ.setdefault("MXNET_AUTOTUNE", "cached")

    # first-contact watchdog: a wedged accelerator tunnel hangs inside
    # PJRT init/dispatch with no Python-level timeout; fail fast with a
    # diagnosis instead of eating the driver's whole time budget
    import threading
    contact = threading.Event()
    try:
        budget = float(os.environ.get("MXNET_BENCH_CONTACT_TIMEOUT",
                                      "600"))
    except ValueError:
        raise SystemExit("MXNET_BENCH_CONTACT_TIMEOUT must be a number "
                         "of seconds (<= 0 disables the watchdog)")
    if budget > 0:
        def watchdog():
            if not contact.wait(budget):
                log(f"bench: FATAL — no device contact within "
                    f"{budget:.0f}s (accelerator tunnel wedged?); "
                    "aborting")
                os._exit(3)
        threading.Thread(target=watchdog, daemon=True).start()

    peak, kind = peak_tflops()
    _flush(jnp.ones((2, 2)).sum())  # one real device round-trip
    contact.set()
    log(f"bench: backend={jax.default_backend()} device={kind} "
        f"peak_bf16={peak} model={model} dtype={dtype}")

    out = {}
    if model in ("all", "resnet50"):
        r = bench_resnet(dtype)
        out.update({
            "metric": "resnet50_v1_train_img_per_sec",
            "value": round(r["img_s"], 2),
            "unit": "img/s",
            "vs_baseline": round(r["img_s"] / BASELINE_IMG_S, 3),
            "dtype": dtype,
            "tflops": round(r["tflops"], 2) if r["tflops"] else None,
            "mfu": round(r["tflops"] / peak, 4)
            if r["tflops"] and peak else None,
            # structural fingerprint (mx.analysis): a throughput drop
            # arrives WITH its program diff — traces, collectives,
            # donated bytes (docs/ANALYSIS.md)
            "resnet_analysis": r.get("analysis"),
            # async-engine observability: input-wait, in-flight window,
            # host syncs inside the timed loop (docs/PERF_NOTES.md)
            "resnet_engine": r.get("engine"),
            # full telemetry story: phase-duration summary, MFU gauge,
            # anomaly count, registry snapshot (docs/OBSERVABILITY.md)
            "resnet_telemetry": r.get("telemetry"),
        })
    if model in ("all", "bert"):
        # isolate: a secondary-model failure must not destroy the
        # primary metric's JSON line
        try:
            b = bench_bert(dtype)
        except Exception as e:
            if model == "bert":
                raise
            log(f"bench[bert]: FAILED ({type(e).__name__}: {e}); "
                "continuing with resnet metrics only")
            b = None
        if b is not None:
            if model == "bert":
                out.update({
                    "metric": "bert_base_train_tokens_per_sec",
                    "value": round(b["tok_s"], 1),
                    "unit": "tokens/s",
                    "vs_baseline": None,  # no in-tree reference number
                    "dtype": dtype,
                })
            out.update({
                "bert_tokens_per_sec": round(b["tok_s"], 1),
                "bert_tflops": round(b["tflops"], 2)
                if b["tflops"] else None,
                "bert_mfu": round(b["tflops"] / peak, 4)
                if b["tflops"] and peak else None,
                "bert_analysis": b.get("analysis"),
                "bert_engine": b.get("engine"),
                "bert_telemetry": b.get("telemetry"),
            })
    for name, fn, tok_field in (("lstm", bench_lstm, "lstm_tokens_per_sec"),
                                ("ssd", bench_ssd, "ssd_img_per_sec")):
        if model not in ("all", name):
            continue
        try:
            r = fn(dtype)
        except Exception as e:
            if model == name:
                raise
            log(f"bench[{name}]: FAILED ({type(e).__name__}: {e}); "
                "continuing without it")
            continue
        val = r.get("tok_s") or r.get("img_s")
        if model == name:
            out.update({
                "metric": f"{name}_train_"
                          + ("tokens_per_sec" if "tok_s" in r
                             else "img_per_sec"),
                "value": round(val, 1),
                "unit": "tokens/s" if "tok_s" in r else "img/s",
                "vs_baseline": None,  # BASELINE rows 4-5: no in-tree number
                "dtype": dtype,
            })
        out.update({
            tok_field: round(val, 1),
            f"{name}_tflops": round(r["tflops"], 2) if r["tflops"] else None,
            f"{name}_mfu": round(r["tflops"] / peak, 4)
            if r["tflops"] and peak else None,
        })
        if r.get("analysis") is not None:
            out[f"{name}_analysis"] = r["analysis"]
        if r.get("engine") is not None:
            out[f"{name}_engine"] = r["engine"]
        if r.get("telemetry") is not None:
            out[f"{name}_telemetry"] = r["telemetry"]
    if model in ("all", "serving"):
        # the serving engine leg (mx.serving): isolate like the other
        # secondary legs — a serving failure must not destroy the
        # training metrics' JSON line
        try:
            s = bench_serving(dtype)
        except Exception as e:
            if model == "serving":
                raise
            log(f"bench[serving]: FAILED ({type(e).__name__}: {e}); "
                "continuing without it")
            s = None
        if s is not None:
            if model == "serving":
                out.update({
                    "metric": "serving_batched_qps",
                    "value": s["qps"],
                    "unit": "req/s",
                    "vs_baseline": s["speedup_vs_unbatched"],
                    "dtype": s["dtype"],
                })
            out.update({
                "serving_qps": s["qps"],
                "serving_p50_ms": s["p50_ms"],
                "serving_p99_ms": s["p99_ms"],
                "serving_batch_fill": s["batch_fill"],
                "serving_unbatched_qps": s["unbatched_qps"],
                "serving_speedup_vs_unbatched":
                    s["speedup_vs_unbatched"],
                "serving_cache_hit_rate":
                    s["compile_cache"]["hit_rate"],
                "serving_goodput_qps": s.get("goodput_qps"),
                "serving_reject_rate": s.get("reject_rate"),
                "serving_deadline_miss_rate":
                    s.get("deadline_miss_rate"),
                "serving_recoveries": s.get("recoveries"),
                "serving_recovery_downtime_s":
                    s.get("recovery_downtime_s"),
                "serving_detail": s,
            })
    if model in ("all", "decode"):
        # continuous-batching decode leg: isolated like the other
        # secondary legs
        try:
            d = bench_decode(dtype)
        except Exception as e:
            if model == "decode":
                raise
            log(f"bench[decode]: FAILED ({type(e).__name__}: {e}); "
                "continuing without it")
            d = None
        if d is not None:
            if model == "decode":
                out.update({
                    "metric": "decode_tokens_per_sec",
                    "value": d["decode_tokens_per_sec"],
                    "unit": "tok/s",
                    "vs_baseline": d["speedup_vs_static"],
                    "dtype": dtype,
                })
            out.update({
                "decode_tokens_per_sec": d["decode_tokens_per_sec"],
                "decode_ttft_p50_ms": d["ttft_p50_ms"],
                "decode_ttft_p99_ms": d["ttft_p99_ms"],
                "decode_tpot_p50_ms": d["tpot_p50_ms"],
                "decode_kv_page_util": d["kv_page_util"],
                "decode_speedup_vs_static": d["speedup_vs_static"],
                "decode_detail": d,
            })
    if model in ("all", "fleet"):
        # serving fleet leg: isolated like the other secondary legs
        try:
            fl = bench_fleet(dtype)
        except Exception as e:
            if model == "fleet":
                raise
            log(f"bench[fleet]: FAILED ({type(e).__name__}: {e}); "
                "continuing without it")
            fl = None
        if fl is not None:
            if model == "fleet":
                out.update({
                    "metric": "fleet_goodput_qps",
                    "value": fl["fleet_goodput_qps"],
                    "unit": "req/s",
                    "vs_baseline": fl["fleet_speedup_vs_single"],
                    "dtype": dtype,
                })
            out.update({
                "fleet_goodput_qps": fl["fleet_goodput_qps"],
                "fleet_speedup_vs_single":
                    fl["fleet_speedup_vs_single"],
                "kill_recovery_downtime_s":
                    fl["kill_recovery_downtime_s"],
                "swap_downtime_s": fl["swap_downtime_s"],
                "fleet_detail": fl,
            })
    try:
        roof = matmul_roofline()
    except Exception as e:
        log(f"bench: roofline probe failed ({type(e).__name__}: {e})")
        roof = None
    out.update({
        "matmul_roofline_tflops": round(roof, 1) if roof else None,
        "peak_tflops": peak,
        "device": kind,
    })
    print(json.dumps(out))


if __name__ == "__main__":
    main()
