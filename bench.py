#!/usr/bin/env python
"""Headline benchmark: Gluon ResNet-50 training throughput + efficiency.

Baseline: reference MXNet-CUDA ResNet-50 training, bs=128 on V100 =
363.69 img/s (docs/static_site/src/pages/api/faq/perf.md:254; BASELINE.md).
The driver runs this on one real TPU chip; vs_baseline is img/s-per-chip
against the V100 row, per BASELINE.json's north star.

Prints ONE JSON line with the primary metric plus efficiency fields:
  {"metric": "resnet50_v1_train_img_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": N, "dtype": "bf16", "tflops": N, "mfu": N,
   "bert_tokens_per_sec": N, "bert_tflops": N, "bert_mfu": N,
   "matmul_roofline_tflops": N, "peak_tflops": N, "device": "..."}

- tflops    = FLOPs actually executed per second: XLA's cost_analysis of
              the one compiled train step (fwd + bwd + update — the whole
              program the chip runs) / 1e12. Note this is the compiled-
              program count, not the "3x forward" analytic convention;
              it is the honest numerator for what the silicon does.
- mfu       = tflops / peak_tflops for the detected TPU generation.
- matmul_roofline_tflops = achieved bf16 GEMM rate of a large square
              matmul on the same chip — the practical ceiling the model
              competes against (distinguishes "framework leaves perf on
              the table" from "platform caps throughput").

The whole training step (forward, loss, backward, SGD-momentum update) is one
donated-buffer XLA computation — the TPU-native answer to the reference's
CachedOp static_alloc + bulking + fused multi_sgd (SURVEY §3.2/§3.4).

AMP note: ``mx.amp.init()`` is enabled AFTER the eager shape-materializing
forward and applies inside the jitted step (one compile). bf16 then FLOWS
between ops (amp/__init__.py), halving HBM activation traffic — the lever
the reference's fp16 row pulls on V100 (perf.md:196,210).

MXNET_BENCH_MODEL=resnet50|bert runs one model only (bert skips the
resnet fields and vice versa); default "all" runs both and emits the
combined line. MXNET_BENCH_DTYPE=fp32 disables AMP.
"""
import json
import os
import sys
import time

import numpy as onp

import jax
import jax.numpy as jnp

BASELINE_IMG_S = 363.69  # V100 fp32 training, bs=128

# bf16 peak TFLOP/s per chip by device_kind substring (public specs).
_PEAK_BF16 = [
    ("v5 lite", 197.0), ("v5litepod", 197.0), ("v5e", 197.0),
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v5p", 459.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _flush(x):
    """Force execution to finish: host-fetch one element (the only reliable
    flush on tunneled platforms where block_until_ready can return before
    execution)."""
    return float(jnp.reshape(x, (-1,))[0])


def peak_tflops():
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if jax.default_backend() == "cpu":
        return None, kind or "cpu"
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak, kind
    return None, kind


def compile_step(step_fn, *args):
    """AOT-compile the train step ONCE; return (callable, flops). The same
    executable drives the timed loop — no second jit compile just to read
    cost_analysis (compiles dominate bench startup on tunneled TPU)."""
    jitted = jax.jit(step_fn, donate_argnums=(0, 1))
    try:
        comp = jitted.lower(*args).compile()
    except Exception as e:  # pragma: no cover - platform-dependent
        log(f"bench: AOT lower/compile unavailable ({type(e).__name__}); "
            "falling back to jit")
        return jitted, None
    flops = None
    try:
        ca = comp.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        f = float(ca.get("flops", 0.0))
        flops = f if f > 0 else None
    except Exception as e:  # pragma: no cover - platform-dependent
        log(f"bench: cost_analysis unavailable ({type(e).__name__})")
    return comp, flops


def matmul_roofline():
    """Achieved bf16 GEMM TFLOP/s: best over several large matmul shapes.
    8192³ underreports the chip by ~40%; the max lives at big-K
    rectangular shapes where the output write is amortized (r5 measured:
    8192x65536x8192 at 163 TFLOP/s = 83% of v5e peak vs 113 for 8192³).
    Skipped on CPU (meaningless there)."""
    if jax.default_backend() == "cpu":
        return None
    best = None
    for m, k, n in ((8192, 8192, 8192), (12288, 12288, 12288),
                    (8192, 65536, 8192), (16384, 32768, 16384)):
        # ~35 TFLOP of work per shape so each probe times comparably
        iters = max(3, int(round(35e12 / (2 * m * k * n))))
        a = jnp.asarray(onp.random.randn(m, k), jnp.bfloat16)
        b = jnp.asarray(onp.random.randn(k, n), jnp.bfloat16)
        f = jax.jit(lambda a, b: a @ b)
        c = f(a, b)
        _flush(c)
        t0 = time.perf_counter()
        for _ in range(iters):
            c = f(a, b)
        _flush(c)
        dt = time.perf_counter() - t0
        tfs = 2 * m * k * n * iters / dt / 1e12
        log(f"bench: roofline probe {m}x{k}x{n} iters={iters}: "
            f"{tfs:.1f} TFLOP/s")
        best = tfs if best is None or tfs > best else best
        del a, b, c
    return best


def bench_resnet(dtype):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from __graft_entry__ import make_train_step, _init_net

    on_accel = jax.default_backend() != "cpu"
    try:
        bs = int(os.environ.get("MXNET_BENCH_BS") or 128) if on_accel \
            else 4
    except ValueError:
        raise SystemExit("MXNET_BENCH_BS must be an integer, got "
                         f"{os.environ['MXNET_BENCH_BS']!r}")
    if bs <= 0:
        raise SystemExit(f"MXNET_BENCH_BS must be positive, got {bs}")
    size = 224 if on_accel else 32
    warmup = 3 if on_accel else 1
    steps = 20 if on_accel else 2

    onp.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    # eager init runs BEFORE amp.init(): the fp32 eager path is
    # compile-cached across runs, while flowing-bf16 eager would trigger
    # ~100 fresh remote compiles on tunneled platforms
    params = _init_net(net, (1, 3, size, size))
    if dtype == "bf16":
        mx.amp.init()
    try:
        train_step = make_train_step(net, params, lr=0.1)

        pd = tuple(jnp.array(p._data._data, copy=True) for p in params)
        mom = tuple(jnp.zeros_like(d) for d in pd)
        x = jnp.asarray(onp.random.uniform(size=(bs, 3, size, size))
                        .astype("float32"))
        y = jnp.asarray(onp.random.randint(0, 1000, size=(bs,))
                        .astype("int32"))
        key = jax.random.PRNGKey(0)

        step, flops = compile_step(train_step, pd, mom, x, y, key)

        t0 = time.perf_counter()
        for _ in range(warmup):
            pd, mom, loss = step(pd, mom, x, y, key)
        _flush(loss)
        log(f"bench: warmup (incl. compile) {time.perf_counter() - t0:.1f}s, "
            f"loss={float(loss):.3f}")

        t0 = time.perf_counter()
        for _ in range(steps):
            pd, mom, loss = step(pd, mom, x, y, key)
        _flush(loss)
        dt = time.perf_counter() - t0
        log(f"bench: final loss={float(loss):.3f}")
    finally:
        if dtype == "bf16":
            mx.amp.uninit()
    img_s = bs * steps / dt
    tfs = flops * steps / dt / 1e12 if flops and on_accel else None
    return {"img_s": img_s, "tflops": tfs, "bs": bs}


def bench_bert(dtype):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import bert
    from __graft_entry__ import make_train_step

    on_accel = jax.default_backend() != "cpu"
    bs, seqlen = (32, 512) if on_accel else (2, 32)
    warmup, steps = (3, 10) if on_accel else (1, 2)
    log(f"bench[bert]: bs={bs} seq={seqlen}")

    onp.random.seed(0)
    net = bert.BERTClassifier(
        bert.bert_base(max_length=seqlen) if on_accel
        else bert.bert_small_test(), num_classes=2)
    vocab = 1000 if on_accel else 128  # stay inside the model's vocab
    tokens = onp.random.randint(0, vocab, size=(1, seqlen)).astype("int32")
    net.initialize()
    net(mx.nd.array(tokens))  # eager init pre-AMP (see bench_resnet note)
    if dtype == "bf16":
        mx.amp.init()
    try:
        params = [p for p in net.collect_params().values()
                  if p._data is not None]
        # lr small enough that random-label steps stay finite on every
        # config (throughput is lr-independent)
        train_step = make_train_step(net, params, lr=1e-3)

        pd = tuple(jnp.array(p._data._data, copy=True) for p in params)
        mom = tuple(jnp.zeros_like(d) for d in pd)
        x = jnp.asarray(onp.random.randint(0, vocab, size=(bs, seqlen))
                        .astype("int32"))
        y = jnp.asarray(onp.random.randint(0, 2, size=(bs,)).astype("int32"))
        key = jax.random.PRNGKey(0)

        step, flops = compile_step(train_step, pd, mom, x, y, key)

        t0 = time.perf_counter()
        for _ in range(warmup):
            pd, mom, loss = step(pd, mom, x, y, key)
        _flush(loss)
        log(f"bench[bert]: warmup {time.perf_counter() - t0:.1f}s, "
            f"loss={float(loss):.3f}")
        t0 = time.perf_counter()
        for _ in range(steps):
            pd, mom, loss = step(pd, mom, x, y, key)
        _flush(loss)
        dt = time.perf_counter() - t0
        log(f"bench[bert]: final loss={float(loss):.3f}")
    finally:
        if dtype == "bf16":
            mx.amp.uninit()
    tok_s = bs * seqlen * steps / dt
    tfs = flops * steps / dt / 1e12 if flops and on_accel else None
    return {"tok_s": tok_s, "tflops": tfs}


def main():
    model = os.environ.get("MXNET_BENCH_MODEL", "all")
    dtype = os.environ.get("MXNET_BENCH_DTYPE", "bf16")
    if dtype not in ("bf16", "fp32"):
        raise SystemExit(f"MXNET_BENCH_DTYPE must be bf16|fp32, got {dtype}")

    # first-contact watchdog: a wedged accelerator tunnel hangs inside
    # PJRT init/dispatch with no Python-level timeout; fail fast with a
    # diagnosis instead of eating the driver's whole time budget
    import threading
    contact = threading.Event()
    try:
        budget = float(os.environ.get("MXNET_BENCH_CONTACT_TIMEOUT",
                                      "600"))
    except ValueError:
        raise SystemExit("MXNET_BENCH_CONTACT_TIMEOUT must be a number "
                         "of seconds (<= 0 disables the watchdog)")
    if budget > 0:
        def watchdog():
            if not contact.wait(budget):
                log(f"bench: FATAL — no device contact within "
                    f"{budget:.0f}s (accelerator tunnel wedged?); "
                    "aborting")
                os._exit(3)
        threading.Thread(target=watchdog, daemon=True).start()

    peak, kind = peak_tflops()
    _flush(jnp.ones((2, 2)).sum())  # one real device round-trip
    contact.set()
    log(f"bench: backend={jax.default_backend()} device={kind} "
        f"peak_bf16={peak} model={model} dtype={dtype}")

    out = {}
    if model in ("all", "resnet50"):
        r = bench_resnet(dtype)
        out.update({
            "metric": "resnet50_v1_train_img_per_sec",
            "value": round(r["img_s"], 2),
            "unit": "img/s",
            "vs_baseline": round(r["img_s"] / BASELINE_IMG_S, 3),
            "dtype": dtype,
            "tflops": round(r["tflops"], 2) if r["tflops"] else None,
            "mfu": round(r["tflops"] / peak, 4)
            if r["tflops"] and peak else None,
        })
    if model in ("all", "bert"):
        # isolate: a secondary-model failure must not destroy the
        # primary metric's JSON line
        try:
            b = bench_bert(dtype)
        except Exception as e:
            if model == "bert":
                raise
            log(f"bench[bert]: FAILED ({type(e).__name__}: {e}); "
                "continuing with resnet metrics only")
            b = None
        if b is not None:
            if model == "bert":
                out.update({
                    "metric": "bert_base_train_tokens_per_sec",
                    "value": round(b["tok_s"], 1),
                    "unit": "tokens/s",
                    "vs_baseline": None,  # no in-tree reference number
                    "dtype": dtype,
                })
            out.update({
                "bert_tokens_per_sec": round(b["tok_s"], 1),
                "bert_tflops": round(b["tflops"], 2)
                if b["tflops"] else None,
                "bert_mfu": round(b["tflops"] / peak, 4)
                if b["tflops"] and peak else None,
            })
    try:
        roof = matmul_roofline()
    except Exception as e:
        log(f"bench: roofline probe failed ({type(e).__name__}: {e})")
        roof = None
    out.update({
        "matmul_roofline_tflops": round(roof, 1) if roof else None,
        "peak_tflops": peak,
        "device": kind,
    })
    print(json.dumps(out))


if __name__ == "__main__":
    main()
