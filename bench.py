#!/usr/bin/env python
"""Headline benchmark: Gluon ResNet-50 training throughput, images/sec.

Baseline: reference MXNet-CUDA ResNet-50 training, bs=128 on V100 =
363.69 img/s (docs/static_site/src/pages/api/faq/perf.md:254; BASELINE.md).
The driver runs this on one real TPU chip; vs_baseline is img/s-per-chip
against the V100 row, per BASELINE.json's north star.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}

The whole training step (forward, loss, backward, SGD-momentum update) is one
donated-buffer XLA computation — the TPU-native answer to the reference's
CachedOp static_alloc + bulking + fused multi_sgd (SURVEY §3.2/§3.4).
"""
import json
import sys
import time

import numpy as onp

import jax
import jax.numpy as jnp

BASELINE_IMG_S = 363.69  # V100 fp32 training, bs=128


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import os
    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu.gluon.model_zoo import vision
    from __graft_entry__ import make_train_step, _init_net

    backend = jax.default_backend()
    on_accel = backend != "cpu"
    bs = 128 if on_accel else 4
    size = 224 if on_accel else 32
    warmup = 3 if on_accel else 1
    steps = 20 if on_accel else 2
    # bf16 AMP by default (the MXU's native mode; reference's own fp16 row
    # shows ~2x over fp32, perf.md:196,210). MXNET_BENCH_DTYPE=fp32 reverts.
    dtype = os.environ.get("MXNET_BENCH_DTYPE", "bf16")
    if dtype not in ("bf16", "fp32"):
        raise SystemExit(f"MXNET_BENCH_DTYPE must be bf16|fp32, got {dtype}")
    if dtype == "bf16":
        mx.amp.init()  # bf16 compute on MXU ops, fp32 master weights
    log(f"bench: backend={backend} bs={bs} size={size} steps={steps} "
        f"dtype={dtype}")

    onp.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    params = _init_net(net, (1, 3, size, size))
    train_step = make_train_step(net, params, lr=0.1)
    step = jax.jit(train_step, donate_argnums=(0, 1))

    # copy the initial buffers: donation must not invalidate the live
    # Parameters still referenced by the Gluon net
    pd = tuple(jnp.array(p._data._data, copy=True) for p in params)
    mom = tuple(jnp.zeros_like(d) for d in pd)
    x = jnp.asarray(onp.random.uniform(size=(bs, 3, size, size))
                    .astype("float32"))
    y = jnp.asarray(onp.random.randint(0, 1000, size=(bs,)).astype("int32"))
    key = jax.random.PRNGKey(0)

    t0 = time.perf_counter()
    for _ in range(warmup):
        pd, mom, loss = step(pd, mom, x, y, key)
    jax.block_until_ready(loss)
    log(f"bench: warmup (incl. compile) {time.perf_counter() - t0:.1f}s, "
        f"loss={float(loss):.3f}")

    t0 = time.perf_counter()
    for _ in range(steps):
        pd, mom, loss = step(pd, mom, x, y, key)
    lv = float(loss)  # host fetch: the only reliable flush on tunneled
    # platforms where block_until_ready can return before execution
    dt = time.perf_counter() - t0
    img_s = bs * steps / dt
    log(f"bench: final loss={lv:.3f}")

    # NOTE on dtype: XLA-on-TPU runs fp32 convs/matmuls as bf16 MXU passes
    # by DEFAULT precision, so fp32 and amp-bf16 throughput are within noise
    # here — the V100's fp16-vs-fp32 2x (perf.md:196,210) has no TPU analog
    # because there is no separate fp32 pipeline to escape from. The metric
    # name stays constant across dtypes so the series (BENCH_r01 →) tracks;
    # the dtype rides in its own field.
    print(json.dumps({
        "metric": "resnet50_v1_train_img_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "dtype": dtype,
    }))


def main_bert():
    """Secondary benchmark (MXNET_BENCH_MODEL=bert): BERT-base MLM-style
    training tokens/sec/chip — the BASELINE.md north-star language metric.
    Flash attention (Pallas on TPU) backs every layer."""
    from mxnet_tpu.gluon.model_zoo import bert
    from __graft_entry__ import make_train_step

    backend = jax.default_backend()
    on_accel = backend != "cpu"
    bs, seqlen = (32, 512) if on_accel else (2, 32)
    warmup, steps = (3, 10) if on_accel else (1, 2)
    log(f"bench[bert]: backend={backend} bs={bs} seq={seqlen}")

    onp.random.seed(0)
    net = bert.BERTClassifier(
        bert.bert_base(max_length=seqlen) if on_accel
        else bert.bert_small_test(), num_classes=2)
    tokens = onp.random.randint(0, 1000, size=(1, seqlen)).astype("int32")
    net.initialize()
    import mxnet_tpu as mx
    net(mx.nd.array(tokens))
    params = [p for p in net.collect_params().values()
              if p._data is not None]
    train_step = make_train_step(net, params, lr=0.01)
    step = jax.jit(train_step, donate_argnums=(0, 1))

    pd = tuple(jnp.array(p._data._data, copy=True) for p in params)
    mom = tuple(jnp.zeros_like(d) for d in pd)
    x = jnp.asarray(onp.random.randint(0, 1000, size=(bs, seqlen))
                    .astype("int32"))
    y = jnp.asarray(onp.random.randint(0, 2, size=(bs,)).astype("int32"))
    key = jax.random.PRNGKey(0)

    t0 = time.perf_counter()
    for _ in range(warmup):
        pd, mom, loss = step(pd, mom, x, y, key)
    jax.block_until_ready(loss)
    log(f"bench[bert]: warmup {time.perf_counter() - t0:.1f}s, "
        f"loss={float(loss):.3f}")
    t0 = time.perf_counter()
    for _ in range(steps):
        pd, mom, loss = step(pd, mom, x, y, key)
    lv = float(loss)  # host fetch flush (see main())
    dt = time.perf_counter() - t0
    tok_s = bs * seqlen * steps / dt
    log(f"bench[bert]: final loss={lv:.3f}")
    print(json.dumps({
        "metric": "bert_base_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": None,  # reference publishes no in-tree BERT number
    }))


if __name__ == "__main__":
    import os
    if os.environ.get("MXNET_BENCH_MODEL", "resnet50") == "bert":
        main_bert()
    else:
        main()
